"""Operator registry.

TPU-native replacement for the reference's NNVM op registry + kernel dispatch
(src/operator/**, ~261 NNVM_REGISTER_OP — SURVEY §2.1 N8). Each op here is a
single *pure JAX function* `fn(*arrays, **attrs) -> array | tuple`. That one
definition serves every consumer the reference needed four kernel backends for:

- eager NDArray calls (per-op `jax.jit` cache → MXU/VPU code via XLA),
- the autograd tape (`jax.vjp` of the same fn gives the backward kernel),
- Symbol/CachedOp graph tracing (fn is traced into the enclosing jit),
- shape/type inference (`jax.eval_shape` replaces FInferShape/FInferType).

Attrs are static (hashable) and participate in the jit cache key — the
equivalent of dmlc::Parameter op schemas (SURVEY §5.6 tier 3).
"""
from __future__ import annotations

import dataclasses
import typing as _t

from ..base import MXNetError

__all__ = ["OpDef", "register", "get", "list_ops", "invoke_jax"]


@dataclasses.dataclass
class OpDef:
    name: str
    fn: _t.Callable
    num_outputs: int = 1          # -1: variadic/tuple output
    needs_rng: bool = False       # fn takes a PRNG key as first argument
    num_visible_outputs: int = None  # outputs exposed to the user (rest are aux,
                                     # e.g. batch_norm's batch stats)
    aliases: tuple = ()
    num_outputs_fn: _t.Callable = None  # attrs -> output count, for variadic
                                        # ops whose arity depends on attrs
                                        # (e.g. Proposal output_score)
    size_attrs: tuple = ()        # attrs whose integer MAGNITUDE creates an
                                  # index space (range_max, one_hot depth,
                                  # Embedding input_dim, arange stop): a
                                  # value past int32-max arms large-tensor
                                  # x64 mode in ndarray.invoke even when
                                  # every input array is small
    host: bool = False            # host-side op: fn takes/returns
                                  # NDArray-level objects eagerly (never
                                  # jitted, not on the tape) — the analogue
                                  # of reference CPU-only FComputeEx ops
                                  # (dgl graph sampling, dgl_graph.cc)

    @property
    def visible_outputs(self):
        return self.num_visible_outputs if self.num_visible_outputs is not None else self.num_outputs


_REGISTRY: dict = {}


def register(name, num_outputs=1, needs_rng=False, num_visible_outputs=None,
             aliases=(), num_outputs_fn=None, host=False, size_attrs=()):
    """Decorator registering a pure-jax op function under `name`."""

    def deco(fn):
        op = OpDef(name, fn, num_outputs, needs_rng, num_visible_outputs,
                   tuple(aliases), num_outputs_fn, tuple(size_attrs), host)
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return fn

    return deco


def get(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError("operator '%s' is not registered" % name) from None


def list_ops():
    return sorted(_REGISTRY)


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def op_key(name, attr_key, kind="op"):
    """The unified-cache key for a per-(op, attrs) executable
    (`mxnet_tpu.compile`, shared with autograd's ``op_bwd`` kind). Custom
    ops carry a ``custom-op:<op_type>`` invalidation tag — re-registering
    the op_type drops every executable that closed over the old callbacks
    (operator.py) — and never persist (the serialized executable would
    embed a process-local `pure_callback` reference); host ops likewise
    stay in-process."""
    from .. import compile as _compile

    op = _REGISTRY.get(name)
    tags = ()
    no_persist = bool(op is not None and op.host)
    if name == "Custom":
        op_type = dict(attr_key).get("op_type")
        tags = ("custom-op:%s" % (op_type,),)
        no_persist = True
    return _compile.ExecutableKey(kind, name, static=attr_key, tags=tags,
                                  no_persist=no_persist)


def _jitted(name, attr_key):
    """Resolve the per-(op, attrs) executable through the unified
    registry (`mxnet_tpu.compile`): telemetry lookup/miss counters,
    ``jit_compile`` events, FLOP accounting and the optional persistent
    tier all ride the registry's fill hook — hits =
    mxtpu_jit_cache_lookup_total - mxtpu_jit_cache_miss_total."""
    from .. import compile as _compile

    def build():
        op = _REGISTRY[name]
        kwargs = dict(attr_key)
        import jax

        def call(*arrays):
            return op.fn(*arrays, **kwargs)

        return jax.jit(call)

    return _compile.get_or_build(op_key(name, attr_key), build, label=name)


def invoke_jax(name, arrays, attrs):
    """Run op `name` on raw jax arrays. Uses the unified per-(op, attrs)
    compiled-executable cache — the analogue of the reference's per-op
    kernel dispatch, with XLA doing codegen + autotuning instead of
    mshadow/cuDNN.

    When any input is a tracer (we are inside an outer jit trace — CachedOp,
    Symbol executor, vjp), the op function is inlined instead of nested-jitted:
    the outer compile fuses everything, and reverse-mode AD through nested jit
    of some primitives (reduce_window max) is unsupported in jax."""
    from .. import engine

    op = _REGISTRY[name]
    if engine.is_naive():
        return op.fn(*arrays, **dict(attrs))
    import jax

    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        return op.fn(*arrays, **dict(attrs))
    attr_key = tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))
    return _jitted(name, attr_key)(*arrays)


# populate the registry
from . import tensor as _tensor  # noqa: E402,F401
from . import nn as _nn  # noqa: E402,F401
from . import random_ops as _random_ops  # noqa: E402,F401
from . import optimizer_ops as _optimizer_ops  # noqa: E402,F401
from . import rnn as _rnn  # noqa: E402,F401
from . import contrib as _contrib  # noqa: E402,F401
from . import linalg as _linalg  # noqa: E402,F401
from . import quantization as _quantization  # noqa: E402,F401
from . import dgl as _dgl  # noqa: E402,F401
from . import image_ops as _image_ops  # noqa: E402,F401
