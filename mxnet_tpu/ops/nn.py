"""Neural-net ops.

TPU-native equivalents of the reference's `src/operator/nn/` family
(fully_connected.cc, convolution.cc, deconvolution.cc, pooling.cc,
batch_norm.cc, layer_norm.cc, activation.cc, softmax.cc, dropout.cc, lrn.cc,
upsampling.cc, softmax_output.cc, l2_normalization.cc — SURVEY §2.1 N8).

Design notes (TPU-first):
- Convs/matmuls lower to `lax.conv_general_dilated` / `jnp.dot` → MXU. Layout
  stays NCHW at the API (reference layout); XLA relayouts internally for TPU.
- There are no cuDNN-vs-native variants: one jax definition; XLA fuses the
  elementwise pre/post ops (bias, activation, BN-inference) into the conv.
- Stateful bits (BatchNorm moving stats) are functional: the op *returns* the
  updated stats as aux outputs and the dispatch layer writes them back
  (OpDef.num_visible_outputs; see ndarray/ndarray.py) — mutation become
  functional outputs, the jit-friendly form of the reference's aux states.
- Ops whose behavior depends on train/predict mode (`BatchNorm`, `Dropout`)
  take an `is_train` attr injected by the dispatch layer from the autograd
  mode (reference: Imperative::is_training / OpContext.is_train).
"""
from __future__ import annotations

import builtins
import functools
import math

import numpy as _np

from . import register
from ..base import MXNetError

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc)
# --------------------------------------------------------------------------

@register("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True):
    if data.ndim < 1:
        raise MXNetError("FullyConnected: data must have at least 1 "
                         "dimension, got shape %s" % (data.shape,))
    if flatten:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    out = jnp.dot(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# --------------------------------------------------------------------------
# Convolution / Deconvolution (reference: convolution.cc, deconvolution.cc)
# --------------------------------------------------------------------------

def _norm_layout(ndim, layout):
    """Resolve a conv/pool layout attr to its string form. None/empty means
    the reference default (channels-first). Supported channels-last forms
    mirror the reference's layout enum (convolution.cc:102 NHWC/NDHWC/NWC —
    reference gates them to GPU; here they lower to XLA dnums directly,
    and on TPU channels-last is the MXU-preferred layout)."""
    spatial = "DHW"[3 - (ndim - 2):]
    if not layout:
        return "NC" + spatial
    layout = str(layout)
    if len(layout) != ndim or set(layout) != set("NC" + spatial):
        raise MXNetError("unsupported layout %r for %dd input" % (layout, ndim))
    return layout


def _channels_last(layout):
    return layout is not None and str(layout).endswith("C") and len(str(layout)) > 2


def _to_ncfirst_perm(ndim):
    """(N, *spatial, C) -> (N, C, *spatial)"""
    return (0, ndim - 1) + tuple(range(1, ndim - 1))


def _to_chlast_perm(ndim):
    """(N, C, *spatial) -> (N, *spatial, C)"""
    return (0,) + tuple(range(2, ndim)) + (1,)


def _pool_window(kernel, stride, pads, ch_last):
    """reduce_window (window, strides, padding) tuples for either layout."""
    if ch_last:
        return ((1,) + tuple(kernel) + (1,),
                (1,) + tuple(stride) + (1,),
                ((0, 0),) + tuple(pads) + ((0, 0),))
    return ((1, 1) + tuple(kernel),
            (1, 1) + tuple(stride),
            ((0, 0), (0, 0)) + tuple(pads))


def _conv_dnums(ndim, layout=None):
    lhs = _norm_layout(ndim, layout)
    if lhs[1] == "C":
        kspec = "OI" + lhs[2:]          # weight (O, I, *k)
    else:
        kspec = "O" + lhs[1:-1] + "I"   # weight (O, *k, I) — reference
        # ConvertLayout(OIHW -> NHWC) convention (convolution.cc:158)
    return lax.conv_dimension_numbers(
        (1,) * ndim, (1,) * ndim, (lhs, kspec, lhs))


def _tup(v, n):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution")
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                num_filter=0, num_group=1, no_bias=False, cudnn_tune=None,
                cudnn_off=False, workspace=1024, layout=None):
    nsp = data.ndim - 2
    ch_last = _channels_last(layout)
    w_spatial = tuple(weight.shape[1:-1] if ch_last else weight.shape[2:])
    # the kernel attr is redundant with the weight's spatial dims; a
    # mismatch is a user error the reference's shape inference rejects
    # (conv shape check, src/operator/nn/convolution.cc InferShape).
    # Validate only when the attr is a clean int sequence — scalar or
    # string forms (foreign-JSON attrs) skip the check rather than crash.
    try:
        kt = tuple(int(k) for k in kernel) if kernel else ()
    except (TypeError, ValueError):
        kt = ()
    if kt and kt != w_spatial:
        raise MXNetError("Convolution: kernel attr %s != weight spatial "
                         "shape %s" % (kt, w_spatial))
    stride = _tup(stride, nsp)
    dilate = _tup(dilate, nsp)
    pad = _tup(pad if pad != () else 0, nsp)
    dn = _conv_dnums(data.ndim, layout)
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        # NOTE: no preferred_element_type here — an fp32-widened primal makes
        # the conv transpose rule pair an fp32 cotangent with bf16 operands and
        # throw under grad. TPU's MXU accumulates bf16 convs in fp32 natively,
        # so bf16-in/bf16-out loses nothing.
    )
    if bias is not None and not no_bias:
        out = out + (bias if ch_last else bias.reshape((1, -1) + (1,) * nsp))
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                  adj=(), target_shape=(), num_filter=0, num_group=1, no_bias=True,
                  cudnn_tune=None, cudnn_off=False, workspace=1024, layout=None):
    """Transposed conv. weight layout (in_c, out_c/g, *k) — same as the
    reference (deconvolution-inl.h); implemented as a fractionally-strided
    conv (lhs_dilation) so XLA lowers it onto the MXU like a regular conv."""
    nsp = data.ndim - 2
    if _channels_last(layout):
        # correctness path only (deconv is off the perf-critical layouts):
        # run the channels-first math and let XLA fold the transposes
        perm_in = _to_ncfirst_perm(data.ndim)
        perm_out = _to_chlast_perm(data.ndim)
        out = deconvolution(
            jnp.transpose(data, perm_in), jnp.transpose(weight, perm_in), bias,
            kernel=kernel, stride=stride, dilate=dilate, pad=pad, adj=adj,
            target_shape=target_shape, num_filter=num_filter,
            num_group=num_group, no_bias=no_bias)
        return jnp.transpose(out, perm_out)
    stride = _tup(stride, nsp)
    dilate = _tup(dilate, nsp)
    pad = _tup(pad if pad != () else 0, nsp)
    adj = _tup(adj if adj != () else 0, nsp)
    if target_shape:
        k = weight.shape[2:]
        adj = tuple(
            target_shape[i] - ((data.shape[2 + i] - 1) * stride[i] - 2 * pad[i]
                               + (dilate[i] * (k[i] - 1) + 1))
            for i in range(nsp))
    in_c = weight.shape[0]
    g = num_group
    # (in_c, oc_g, *k) -> (g, in_c/g, oc_g, *k) -> (g, oc_g, in_c/g, *k) -> (out_c, in_c/g, *k)
    w = weight.reshape((g, in_c // g) + weight.shape[1:])
    w = jnp.swapaxes(w, 1, 2)
    w = w.reshape((g * weight.shape[1], in_c // g) + weight.shape[2:])
    w = jnp.flip(w, axis=tuple(range(2, 2 + nsp)))
    k_eff = [dilate[i] * (weight.shape[2 + i] - 1) + 1 for i in range(nsp)]
    padding = [(k_eff[i] - 1 - pad[i], k_eff[i] - 1 - pad[i] + adj[i]) for i in range(nsp)]
    dn = _conv_dnums(data.ndim)
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nsp,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=g,
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


# --------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc)
# --------------------------------------------------------------------------

def _extract_patches(x, kernel, stride, pads, pad_value):
    """Channels-first window unfold: (N, C, prod(k), *out_spatial). Shared
    by _patches_max and the large-kernel maxpool backward fallback so the
    dimension_numbers/reshape layout stays in lockstep. Pad value must be
    finite when the result feeds arithmetic: conv_general_dilated_patches
    gathers through a one-hot conv, and 0 * -inf = NaN would poison every
    border window."""
    n, c = x.shape[0], x.shape[1]
    padded = jnp.pad(x, ((0, 0), (0, 0)) + tuple(pads),
                     constant_values=pad_value)
    patches = lax.conv_general_dilated_patches(
        padded, filter_shape=kernel, window_strides=stride,
        padding=[(0, 0)] * len(kernel),
        dimension_numbers=_conv_dnums(x.ndim))
    return patches.reshape(
        (n, c, int(_np.prod(kernel))) + patches.shape[2:])


def _patches_max(x, kernel, stride, pads):
    """Max pool via patch extraction — differentiable formulation used only
    inside the backward rule of `_float_max_pool`."""
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
    return _extract_patches(x, kernel, stride, pads, neg).max(axis=2)


def _max_pool_taps_bwd(x, y, g, kernel, stride, pads):
    """Channels-first maxpool input-grad as a pure elementwise expression.

    dx[p] = sum over windows w containing p of [x[p] == y[w]] * g[w].
    For tap offset a in prod(kernel), the window touching padded position
    q = w*s + a is read by zero-stuffing y/g onto the padded input grid
    (lax.pad with interior dilation s-1, offset a). All prod(k) terms are
    compare/select/adds that XLA fuses into ONE kernel — ~1 read of x and
    1 write of dx vs the old patches-based vjp, which rebuilt
    conv_general_dilated_patches in backward (a k^2*C-channel one-hot conv:
    0.5 TFLOP and ~12 ms/step of the round-4 bs256 ResNet-50 profile for
    the single stem maxpool).

    Tie semantics: every in-window position equal to the max receives the
    full window cotangent (reference CPU pooling backward behavior,
    src/operator/nn/pool.h max path), vs the even split jnp.max's vjp gave
    the old formulation. Ties are measure-zero for float activations."""
    nsp = len(kernel)
    xshape = x.shape[2:]
    oshape = y.shape[2:]
    padded = tuple(xshape[i] + pads[i][0] + pads[i][1] for i in range(nsp))
    ninf = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (0, 0)) + tuple(pads))
    dxp = jnp.zeros_like(xp)
    import itertools
    for taps in itertools.product(*[range(k) for k in kernel]):
        cfg = []
        ok = True
        for i in range(nsp):
            hi = padded[i] - taps[i] - ((oshape[i] - 1) * stride[i] + 1)
            if hi < 0:  # tap runs past the padded edge for every window
                ok = False
                break
            cfg.append((taps[i], hi, stride[i] - 1))
        if not ok:
            continue
        cfg = ((0, 0, 0), (0, 0, 0)) + tuple(cfg)
        up_y = lax.pad(y, ninf, cfg)
        up_g = lax.pad(g, jnp.zeros((), g.dtype), cfg)
        dxp = dxp + jnp.where(xp == up_y, up_g, jnp.zeros((), g.dtype))
    sl = (slice(None), slice(None)) + tuple(
        slice(pads[i][0], pads[i][0] + xshape[i]) for i in range(nsp))
    return dxp[sl]


@functools.lru_cache(maxsize=None)
def _float_max_pool(kernel, stride, pads, ch_last=False):
    """Float max pooling: cheap `lax.reduce_window` forward, custom
    backward (reduce_window(max)'s own grad lowers to TPU SelectAndScatter,
    which serializes; the tap-mask expression below stays elementwise)."""
    window, strides, padding = _pool_window(kernel, stride, pads, ch_last)

    nsp = len(kernel)
    to_ncfirst = _to_ncfirst_perm(nsp + 2)
    to_chlast = _to_chlast_perm(nsp + 2)

    @jax.custom_vjp
    def mp(x):
        return lax.reduce_window(x, _np.asarray(-_np.inf, x.dtype), lax.max,
                                 window, strides, padding)

    def fwd(x):
        y = mp(x)
        return y, (x, y)

    def bwd(res, g):
        x, y = res
        if ch_last:
            x = jnp.transpose(x, to_ncfirst)
            y = jnp.transpose(y, to_ncfirst)
            g = jnp.transpose(g, to_ncfirst)
        out_sp = y.shape[2:]
        covers = all(
            kernel[i] >= x.shape[2 + i] + pads[i][0] + pads[i][1]
            for i in range(nsp))
        if all(o == 1 for o in out_sp) and covers:
            # single window COVERING the padded input (global pool): one
            # broadcast compare. The coverage check matters: a 2x2/s2
            # window on a 3x3 input also has 1x1 output but never reads
            # the last row/col, which must not receive gradient.
            dx = jnp.where(x == y, g, jnp.zeros((), g.dtype))
        elif int(_np.prod(kernel)) <= 32:
            dx = _max_pool_taps_bwd(x, y, g, kernel, stride, pads)
        else:
            # large overlapping kernels (rare): patches-based fallback,
            # with the same full-credit tie semantics as the taps path
            # (explicit equality mask instead of jnp.max's even-split vjp;
            # the patch extraction itself is linear, so only it is vjp'd)
            patches, pull = jax.vjp(
                lambda t: _extract_patches(t, kernel, stride, pads, 0), x)
            mask = patches == y[:, :, None]
            dx = pull(jnp.where(mask, g[:, :, None],
                                jnp.zeros((), g.dtype)))[0]
        if ch_last:
            dx = jnp.transpose(dx, to_chlast)
        return (dx,)

    mp.defvjp(fwd, bwd)
    return mp


@register("Pooling")
def pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(), pad=(),
            pooling_convention="valid", count_include_pad=True, p_value=2,
            cudnn_off=False, layout=None):
    nsp = data.ndim - 2
    ch_last = _channels_last(layout)
    sp_off = 1 if ch_last else 2  # first spatial axis position
    if global_pool:
        kernel = data.shape[sp_off:sp_off + nsp]
        stride = (1,) * nsp
        pad = (0,) * nsp
    kernel = _tup(kernel, nsp)
    stride = _tup(stride if stride != () else 1, nsp)
    pad = _tup(pad if pad != () else 0, nsp)
    pads = []
    for i in range(nsp):
        lo = hi = pad[i]
        if pooling_convention == "full" and not global_pool:
            size = data.shape[sp_off + i] + 2 * pad[i] - kernel[i]
            out_d = int(math.ceil(size / stride[i])) + 1
            need = (out_d - 1) * stride[i] + kernel[i] - (data.shape[sp_off + i] + 2 * pad[i])
            hi += builtins.max(need, 0)
        pads.append((lo, hi))
    window, strides, padding = _pool_window(kernel, stride, tuple(pads), ch_last)

    if pool_type == "max":
        if not jnp.issubdtype(data.dtype, jnp.floating):
            init = jnp.iinfo(data.dtype).min
            return lax.reduce_window(data, _np.asarray(init, data.dtype), lax.max,
                                     window, strides, padding)
        return _float_max_pool(kernel, stride, tuple(pads), ch_last)(data)
    if pool_type == "lp":
        powed = jnp.power(jnp.abs(data), p_value)
        s = lax.reduce_window(powed, _np.zeros((), data.dtype), lax.add, window, strides, padding)
        return jnp.power(s, 1.0 / p_value)
    s = lax.reduce_window(data, _np.zeros((), data.dtype), lax.add, window, strides, padding)
    if pool_type == "sum":
        return s
    # avg
    if count_include_pad:
        denom = float(_np.prod(kernel))
        return s / jnp.asarray(denom, data.dtype)
    ones = jnp.ones(data.shape, data.dtype)
    cnt = lax.reduce_window(ones, _np.zeros((), data.dtype), lax.add, window, strides, padding)
    return s / cnt


# --------------------------------------------------------------------------
# Normalization (batch_norm.cc, layer_norm.cc, instance_norm.cc, l2_norm...)
# --------------------------------------------------------------------------

def _bn_axes(ndim, ax):
    red = tuple(i for i in range(ndim) if i != ax)
    bshape_fn = lambda shape: tuple(  # noqa: E731
        shape[ax] if i == ax else 1 for i in range(ndim))
    return red, bshape_fn


def _bn_stats(data, red):
    """Per-channel batch mean/var in ONE fused HBM pass over `data`.

    Both reductions consume the same read (XLA multi-output-fuses them;
    jnp.var's mean-subtracted two-pass re-reads the activation — GBs per BN
    layer at train bs>=256). Raw E[x^2]-E[x]^2 cancels catastrophically for
    large-mean/small-spread channels, so shift by a per-channel proxy of
    the batch mean first: the mean over ONE slice of the leading reduced
    dim (an O(1/N) read), within ~std/sqrt(HW) of the true channel mean
    for any input. The f32 cast of `data` here is consumed ONLY inside the
    fused reductions, so no f32 copy of the activation is materialized —
    keeping it out of the normalize path is what lets every conv output
    stay a single bf16 tensor (round-4 profile: the old shared x32 cast
    made XLA emit (f32, bf16) pairs out of every conv fusion, 3x the
    write bytes)."""
    lead = red[0]  # first reduced dim (batch unless axis==0)
    proxy = jnp.mean(
        lax.slice_in_dim(data, 0, 1, axis=lead).astype(jnp.float32),
        axis=red, keepdims=True)
    d = data.astype(jnp.float32) - proxy
    s1 = jnp.mean(d, axis=red)
    s2 = jnp.mean(jnp.square(d), axis=red)
    mean = proxy.reshape(s1.shape) + s1
    var = jnp.maximum(s2 - jnp.square(s1), 0.0)
    return mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train(data, gamma, beta, ax, eps, fix_gamma):
    return _bn_train_fwd(data, gamma, beta, ax, eps, fix_gamma)[0]


def _bn_train_fwd(data, gamma, beta, ax, eps, fix_gamma):
    red, bshape_fn = _bn_axes(data.ndim, ax)
    bshape = bshape_fn(data.shape)
    mean, var = _bn_stats(data, red)
    inv = lax.rsqrt(var + eps)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = g.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean * scale
    dt = data.dtype
    # the big-tensor math is ONE fused FMA in the input dtype; per-channel
    # scale/shift are computed in f32 (cheap, accurate) then rounded once
    out = (data * scale.astype(dt).reshape(bshape)
           + shift.astype(dt).reshape(bshape))
    return (out, mean, var), (data, gamma, beta, mean, inv)


def _bn_train_bwd(ax, eps, fix_gamma, res, cts):
    """Hand-written BN train backward, bandwidth-lean (round-4 MFU work):
    all full-tensor math stays in the input dtype; dgamma/dbeta accumulate
    in f32 inside fused convert-reduces; the correction terms ride C-sized
    f32 vectors. Cotangents for the mean/var outputs are ignored: they feed
    the moving-stat buffers (never differentiated); differentiating through
    output_mean_var stats is unsupported (documented divergence)."""
    data, gamma, beta, mean, inv = res
    ct = cts[0]
    red, bshape_fn = _bn_axes(data.ndim, ax)
    bshape = bshape_fn(data.shape)
    n = 1
    for i in red:
        n *= data.shape[i]
    dt = data.dtype
    xhat = ((data - mean.astype(dt).reshape(bshape))
            * inv.astype(dt).reshape(bshape))
    dbeta = jnp.sum(ct, axis=red, dtype=jnp.float32)
    dgamma = jnp.sum(ct * xhat, axis=red, dtype=jnp.float32)
    g32 = (jnp.ones_like(inv) if fix_gamma
           else gamma.astype(jnp.float32))
    coef = (g32 * inv).astype(dt).reshape(bshape)
    c_b = (dbeta / n).astype(dt).reshape(bshape)
    c_g = (dgamma / n).astype(dt).reshape(bshape)
    dx = coef * (ct - c_b - xhat * c_g)
    dgamma_out = (jnp.zeros_like(gamma) if fix_gamma
                  else dgamma.astype(gamma.dtype))
    return dx, dgamma_out, dbeta.astype(beta.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _bn_frozen(data, gamma, beta, mean, var, ax, eps, fix_gamma):
    return _bn_frozen_fwd(data, gamma, beta, mean, var, ax, eps, fix_gamma)[0]


def _bn_frozen_fwd(data, gamma, beta, mean, var, ax, eps, fix_gamma):
    red, bshape_fn = _bn_axes(data.ndim, ax)
    bshape = bshape_fn(data.shape)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = g.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    dt = data.dtype
    out = (data * scale.astype(dt).reshape(bshape)
           + shift.astype(dt).reshape(bshape))
    return out, (data, gamma, beta, mean, var)


def _bn_frozen_bwd(ax, eps, fix_gamma, res, ct):
    data, gamma, beta, mean, var = res
    red, bshape_fn = _bn_axes(data.ndim, ax)
    bshape = bshape_fn(data.shape)
    dt = data.dtype
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    g32 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    dx = ct * (g32 * inv).astype(dt).reshape(bshape)
    dbeta = jnp.sum(ct, axis=red, dtype=jnp.float32)
    if fix_gamma:
        dgamma = jnp.zeros_like(gamma)
    else:
        xhat = ((data - mean.astype(dt).reshape(bshape))
                * inv.astype(dt).reshape(bshape))
        dgamma = jnp.sum(ct * xhat, axis=red,
                         dtype=jnp.float32).astype(gamma.dtype)
    return (dx, dgamma, dbeta.astype(beta.dtype),
            jnp.zeros_like(mean), jnp.zeros_like(var))


_bn_frozen.defvjp(_bn_frozen_fwd, _bn_frozen_bwd)


def _conv_epilogue_enabled():
    """Fused Pallas conv-epilogue (BN stats+normalize+ReLU+add): default on
    for SINGLE-device TPU; MXTPU_PALLAS_CONV_EPILOGUE=1 forces it
    everywhere (interpret mode off-TPU, and regardless of device count),
    =0 disables everywhere.

    auto excludes multi-device runs: pallas_call has no SPMD partitioning
    rule, so under pjit with a sharded batch axis it would force XLA to
    gather each BN's full activation per layer — the jnp fallback keeps
    the documented free-psum sync-BN behavior there."""
    from .. import env as _env_mod

    env = _env_mod.get("MXTPU_PALLAS_CONV_EPILOGUE")
    if env == "0":
        return False
    if env == "1":
        return True
    import jax as _jax

    return (_jax.default_backend() == "tpu"
            and _jax.device_count() == 1)


def _bn_act(data, addend, gamma, beta, moving_mean, moving_var, eps, momentum,
            fix_gamma, use_global_stats, axis, act, is_train):
    """Shared BatchNorm(+add)(+ReLU) core behind BatchNorm /
    BatchNormRelu / BatchNormAddRelu.

    Training path: when the Pallas conv-epilogue is enabled and the channel
    axis is last (the NHWC bench layout — flattening to (R, C) is free),
    the whole epilogue runs as the two-pass fused kernel pair
    (pallas_kernels.conv_epilogue); otherwise the pure-jnp fallback keeps
    the existing custom-vjp BN with separate add/relu ops (XLA fuses the
    elementwise tail, but offers no cross-pass guarantee — see
    docs/perf_evidence/conv_epilogue.md)."""
    ax = axis % data.ndim
    eps = float(eps)
    fix_gamma = bool(fix_gamma)
    relu = act == "relu"
    if is_train and not use_global_stats:
        use_pallas = False
        if _conv_epilogue_enabled() and ax == data.ndim - 1:
            from . import pallas_kernels

            use_pallas = pallas_kernels.conv_epilogue_fits(
                data.shape[ax], jnp.dtype(data.dtype).itemsize)
        if use_pallas:
            out, mean, var = pallas_kernels.conv_epilogue(
                data, gamma, beta, addend, eps=eps, fix_gamma=fix_gamma,
                relu=relu)
        else:
            out, mean, var = _bn_train(data, gamma, beta, ax, eps, fix_gamma)
            if addend is not None:
                out = out + addend
            if relu:
                out = jax.nn.relu(out)
        new_mm = (moving_mean * momentum
                  + mean.astype(moving_mean.dtype) * (1 - momentum))
        new_mv = (moving_var * momentum
                  + var.astype(moving_var.dtype) * (1 - momentum))
        return out, new_mm, new_mv
    out = _bn_frozen(data, gamma, beta, moving_mean, moving_var, ax,
                     eps, fix_gamma)
    if addend is not None:
        out = out + addend
    if relu:
        out = jax.nn.relu(out)
    return out, moving_mean, moving_var


@register("BatchNorm", num_outputs=3, num_visible_outputs=1)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False,
               axis=1, cudnn_off=False, is_train=False):
    """Returns (out, new_moving_mean, new_moving_var); the dispatch layer
    writes outputs 1..2 back into the aux-state arrays (reference mutates aux
    in place, src/operator/nn/batch_norm.cc).

    Both paths use a hand-written custom_vjp (see _bn_train/_bn_frozen; the
    channels-last training path upgrades to the fused Pallas epilogue
    kernels under MXTPU_PALLAS_CONV_EPILOGUE — see _bn_act): full-tensor
    math runs in the input dtype end to end (bf16 under AMP), per-channel
    vectors and reduction accumulators in f32. Under pjit with a sharded
    batch axis the stats reductions psum across replicas automatically (the
    reference's SyncBatchNorm, sync_batch_norm.cc, falls out of GSPMD) —
    which is why the Pallas fused path is gated to single-device runs
    (_conv_epilogue_enabled); multi-device always takes the jnp path."""
    return _bn_act(data, None, gamma, beta, moving_mean, moving_var, eps,
                   momentum, fix_gamma, use_global_stats, axis, None,
                   is_train)


@register("BatchNormRelu", aliases=("_contrib_BatchNormRelu",),
          num_outputs=3, num_visible_outputs=1)
def batch_norm_relu(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, axis=1, act_type="relu",
                    cudnn_off=False, is_train=False):
    """BatchNorm + activation as ONE op (TPU fused conv-epilogue; the
    reference's cuDNN-fused BNActivation analogue). Under
    MXTPU_PALLAS_CONV_EPILOGUE the training path runs the two-pass Pallas
    kernel pair instead of separate normalize and ReLU HBM passes."""
    if act_type not in ("relu",):
        raise MXNetError("BatchNormRelu: unsupported act_type %r" % act_type)
    return _bn_act(data, None, gamma, beta, moving_mean, moving_var, eps,
                   momentum, fix_gamma, use_global_stats, axis, act_type,
                   is_train)


@register("BatchNormAddRelu", aliases=("_contrib_BatchNormAddRelu",),
          num_outputs=3, num_visible_outputs=1)
def batch_norm_add_relu(data, addend, gamma, beta, moving_mean, moving_var,
                        eps=1e-3, momentum=0.9, fix_gamma=True,
                        use_global_stats=False, output_mean_var=False,
                        axis=1, act_type="relu", cudnn_off=False,
                        is_train=False):
    """BatchNorm + residual add + ReLU as ONE op — the ResNet block tail
    (reference: the cuDNN BNAddRelu fusion, contrib BatchNormAddRelu).
    `addend` joins after normalization, before the activation:
    out = relu(bn(data) + addend)."""
    if act_type not in ("relu",):
        raise MXNetError("BatchNormAddRelu: unsupported act_type %r"
                         % act_type)
    return _bn_act(data, addend, gamma, beta, moving_mean, moving_var, eps,
                   momentum, fix_gamma, use_global_stats, axis, act_type,
                   is_train)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_core(data, gamma, beta, ax, eps):
    return _ln_core_fwd(data, gamma, beta, ax, eps)[0]


def _ln_core_fwd(data, gamma, beta, ax, eps):
    """Row-stat LayerNorm, same bandwidth discipline as _bn_train: the f32
    cast lives only inside the fused row reductions (no f32 copy of the
    activation materializes); the normalize is input-dtype math with the
    per-row mean/inv rounded once. One fused read computes both moments,
    shifted by a per-row proxy (the row's first element) so the
    E[d^2]-E[d]^2 form cannot cancel catastrophically for
    large-mean/small-spread rows."""
    proxy = lax.slice_in_dim(data, 0, 1, axis=ax).astype(jnp.float32)
    d = data.astype(jnp.float32) - proxy
    s1 = jnp.mean(d, axis=ax, keepdims=True)
    s2 = jnp.mean(jnp.square(d), axis=ax, keepdims=True)
    mean = proxy + s1
    var = jnp.maximum(s2 - jnp.square(s1), 0.0)
    inv = lax.rsqrt(var + eps)
    dt = data.dtype
    bshape = tuple(data.shape[ax] if i == ax else 1
                   for i in range(data.ndim))
    xhat = (data - mean.astype(dt)) * inv.astype(dt)
    out = (xhat * gamma.astype(dt).reshape(bshape)
           + beta.astype(dt).reshape(bshape))
    return out, (data, gamma, beta, mean, inv)


def _ln_core_bwd(ax, eps, res, ct):
    data, gamma, beta, mean, inv = res
    dt = data.dtype
    ndim = data.ndim
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(ndim))
    red = tuple(i for i in range(ndim) if i != ax)
    xhat = (data - mean.astype(dt)) * inv.astype(dt)
    dgamma = jnp.sum(ct * xhat, axis=red, dtype=jnp.float32)
    dbeta = jnp.sum(ct, axis=red, dtype=jnp.float32)
    g = ct * gamma.astype(dt).reshape(bshape)
    # row-wise corrections in f32 (per-row vectors are cheap)
    m1 = jnp.mean(g.astype(jnp.float32), axis=ax, keepdims=True)
    m2 = jnp.mean((g * xhat).astype(jnp.float32), axis=ax, keepdims=True)
    dx = inv.astype(dt) * (g - m1.astype(dt) - xhat * m2.astype(dt))
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype))


_ln_core.defvjp(_ln_core_fwd, _ln_core_bwd)


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    ax = axis % data.ndim
    return _ln_core(data, gamma, beta, ax, float(eps))


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
    else:  # spatial
        red = tuple(range(2, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / n


@register("LRN")
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    sq = jnp.square(data)
    half = nsize // 2
    window = (1, nsize, 1, 1)
    s = lax.reduce_window(sq, _np.zeros((), data.dtype), lax.add, window,
                          (1, 1, 1, 1), [(0, 0), (half, half), (0, 0), (0, 0)])
    return data / jnp.power(knorm + (alpha / nsize) * s, beta)


# --------------------------------------------------------------------------
# Activations (activation.cc, leaky_relu.cc)
# --------------------------------------------------------------------------

@register("Activation")
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %s" % act_type)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        bshape = (1, -1) + (1,) * (data.ndim - 2) if data.ndim > 1 else (-1,)
        return jnp.where(data >= 0, data, gamma.reshape(bshape) * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError("unknown act_type %s" % act_type)


@register("im2col")
def im2col(data, kernel=(), stride=(), dilate=(), pad=()):
    """Sliding-window patch extraction (reference: src/operator/nn/im2col.cc
    — the building block DeformableConvolution/custom convs use). data
    (N, C, H, W) -> (N, C*prod(kernel), L) column matrix."""
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    n, c = data.shape[0], data.shape[1]
    patches = lax.conv_general_dilated_patches(
        data, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw))               # (N, C*kh*kw, OH, OW)
    oh, ow = patches.shape[2], patches.shape[3]
    return patches.reshape(n, c * kh * kw, oh * ow)


@register("col2im")
def col2im(data, output_size=(), kernel=(), stride=(), dilate=(), pad=()):
    """Scatter-add columns back into an image — im2col's exact transpose
    (reference: im2col.cc col2im). Implemented as the vjp of im2col, which
    XLA lowers to one scatter-add."""
    h, w = output_size
    n = data.shape[0]
    kh, kw = kernel
    c = data.shape[1] // (kh * kw)

    def f(img):
        sh, sw = stride if stride else (1, 1)
        dh, dw = dilate if dilate else (1, 1)
        ph, pw = pad if pad else (0, 0)
        patches = lax.conv_general_dilated_patches(
            img, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
            rhs_dilation=(dh, dw))
        return patches.reshape(n, c * kh * kw, -1)

    _, pull = jax.vjp(f, jnp.zeros((n, c, h, w), data.dtype))
    return pull(data)[0]


# --------------------------------------------------------------------------
# Softmax family (softmax.cc, softmax_output.cc, loss_binary_op.cc)
# --------------------------------------------------------------------------

@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    x = data if temperature in (None, 1.0) else data / temperature
    if length is not None:
        steps = jnp.arange(data.shape[axis])
        bshape = [1] * data.ndim
        bshape[axis] = data.shape[axis]
        mask = steps.reshape(bshape) < length.reshape((-1,) + (1,) * (data.ndim - 1))
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


@register("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0, multi_output=False,
                   use_ignore=False, preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0):
    """Fused softmax + cross-entropy gradient: forward is softmax, backward is
    (p - onehot(label)) — the reference computes this in SoftmaxOutput's
    backward (src/operator/softmax_output-inl.h)."""
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(d, l):
        return jax.nn.softmax(d, axis=axis)

    def fwd(d, l):
        out = jax.nn.softmax(d, axis=axis)
        return out, (out, l)

    def bwd(res, g):
        out, lab = res
        depth = out.shape[axis]
        li = lab.astype(jnp.int32)
        onehot = jax.nn.one_hot(li, depth, axis=axis, dtype=out.dtype)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (depth - 1) * (1 - onehot)
        grad = out - onehot
        valid = None
        if use_ignore:
            keep = (li != int(ignore_label)).astype(out.dtype)
            grad = grad * jnp.expand_dims(keep, axis if axis != -1 else li.ndim)
            valid = jnp.sum(keep)
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            grad = grad / (jnp.maximum(valid, 1.0) if valid is not None else out.shape[0])
        return grad * grad_scale, jnp.zeros_like(lab)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return (d - l.reshape(d.shape)) * grad_scale, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return jax.nn.sigmoid(d)

    def fwd(d, l):
        out = jax.nn.sigmoid(d)
        return out, (out, l)

    def bwd(res, g):
        out, l = res
        return ((out - l.reshape(out.shape)) * grad_scale, jnp.zeros_like(l))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return (jnp.sign(d - l.reshape(d.shape)) * grad_scale, jnp.zeros_like(l))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    @jax.custom_vjp
    def f(d):
        return d

    def fwd(d):
        return d, d.shape

    def bwd(shape, g):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / shape[0]
        return (jnp.full(shape, scale, dtype=jnp.float32),)

    f.defvjp(fwd, bwd)
    return f(data)


# --------------------------------------------------------------------------
# Dropout (dropout.cc) — rng-consuming op
# --------------------------------------------------------------------------

@register("Dropout", needs_rng=True)
def dropout(rng, data, p=0.5, mode="training", axes=(), cudnn_off=False, is_train=False):
    if (not is_train and mode != "always") or p <= 0.0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# --------------------------------------------------------------------------
# UpSampling / resize (upsampling.cc, bilinear via jax.image)
# --------------------------------------------------------------------------

@register("UpSampling")
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=512):
    data = args[0]
    if sample_type == "nearest":
        outs = []
        for d in args:
            s = scale if outs == [] else data.shape[2] * scale // d.shape[2]
            outs.append(jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3))
        if len(outs) == 1:
            return outs[0]
        if multi_input_mode == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    # bilinear: args = (data, weight) in reference; we resize directly
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------

@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet-style correlation (reference:
    src/operator/correlation-inl.h / correlation.cc). For each displacement
    (dy, dx) on the stride2 grid within ±max_displacement, correlates a
    kernel_size² patch of data1 with the displaced patch of data2, averaged
    over channels and patch. Output channel order is dy-major, matching the
    reference's neighborhood-grid layout. Implemented as a static Python
    loop over the (small) displacement grid of shifted elementwise products
    + one reduce_window box filter each — everything fuses under XLA."""
    import numpy as _onp

    b, c, h, w = data1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    pad2 = [(0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)]
    p1 = jnp.pad(data1, pad2)
    p2 = jnp.pad(data2, pad2)
    ph, pw = h + 2 * pad_size, w + 2 * pad_size
    out_h = int(_onp.ceil((ph - 2 * border) / stride1))
    out_w = int(_onp.ceil((pw - 2 * border) / stride1))
    rad = max_displacement // stride2
    # extra pad so every displaced slice of p2 is in-bounds
    p2x = jnp.pad(p2, [(0, 0), (0, 0),
                       (max_displacement, max_displacement),
                       (max_displacement, max_displacement)])
    norm = float(c * kernel_size * kernel_size)
    chans = []
    for dy in range(-rad, rad + 1):
        for dx in range(-rad, rad + 1):
            oy, ox = dy * stride2, dx * stride2
            shifted = lax.dynamic_slice(
                p2x, (0, 0, max_displacement + oy, max_displacement + ox),
                (b, c, ph, pw))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            box = lax.reduce_window(
                prod, 0.0, lax.add,
                window_dimensions=(1, c, kernel_size, kernel_size),
                window_strides=(1, c, 1, 1), padding="VALID")
            # box[y'] sums the window STARTING at y'; a window centered at
            # y starts at y - kr
            sl = lax.slice(
                box, (0, 0, border - kr, border - kr),
                (b, 1, border - kr + (out_h - 1) * stride1 + 1,
                 border - kr + (out_w - 1) * stride1 + 1),
                (1, 1, stride1, stride1))
            chans.append(sl / norm)
    return jnp.concatenate(chans, axis=1)


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001, momentum=0.9):
    return data
