"""DGL graph operators (reference: src/operator/contrib/dgl_graph.cc).

Graph-sampling preprocessing for DGL-style GNN training: csr neighbor
sampling (uniform + weighted), vertex-induced subgraphs, subgraph
compaction, edge-id lookup and adjacency conversion.

These are HOST ops (`host=True`): the reference implements them CPU-only
(`FComputeEx<cpu>` — dgl_graph.cc:800,:1172) because they are inherently
data-dependent pointer-chasing over CSR structures that feed the data
pipeline, not accelerator compute. Here they run as eager numpy over the
CSRNDArray components, with the sampled minibatch graphs then moving to
the device for the actual GNN math. RNG flows from the framework key
chain (seed()-reproducible).

Layout conventions (mirroring the reference docstrings):
- sampled vertex arrays are (max_num_vertices+1,) int64, front-packed
  sorted ids with the LAST element holding the actual count;
- sampled subgraph CSRs are (max_num_vertices, max_num_vertices): row i
  holds the sampled out-edges of the i-th sampled vertex (position
  space), columns are ORIGINAL vertex ids, values are the parent edge
  values ("empty rows at the end and many empty columns" — the state
  dgl_graph_compact exists to clean up, dgl_graph.cc:1551);
- layer arrays give the BFS hop at which each vertex entered the sample.
"""
from __future__ import annotations

import numpy as _np

from . import register

__all__ = []


def _np_csr(csr):
    return (_np.asarray(csr.data.asnumpy()),
            _np.asarray(csr.indices.asnumpy()).astype(_np.int64),
            _np.asarray(csr.indptr.asnumpy()).astype(_np.int64),
            tuple(csr.shape))


def _mk_csr(data, indptr, indices, shape, like, dtype=None):
    from ..ndarray import sparse

    return sparse.csr_matrix(
        (data, indices, indptr), shape=shape, ctx=like.context,
        dtype=dtype if dtype is not None else data.dtype)


def _mk_nd(arr, like):
    from .. import ndarray as nd

    return nd.array(arr, ctx=like.context, dtype=arr.dtype)


def _rng_from_key(key):
    import jax

    try:
        raw = _np.asarray(jax.random.key_data(key))
    except Exception:  # noqa: BLE001 — raw uint32 key arrays
        raw = _np.asarray(key)
    return _np.random.default_rng(int(raw.astype(_np.uint64).sum()))


def _neighbor_sample(rs, data, indices, indptr, seeds, prob, num_hops,
                     num_neighbor, max_num_vertices):
    """BFS from `seeds`; each expanded vertex keeps `num_neighbor`
    sampled out-edges. Returns (sorted vertex ids, {vid: hop},
    {vid: [(col, value)]})."""
    layer = {}
    for v in seeds:
        v = int(v)
        if len(layer) >= max_num_vertices:
            break
        layer.setdefault(v, 0)
    frontier = list(layer)
    edges = {}
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            row = indices[indptr[v]:indptr[v + 1]]
            vals = data[indptr[v]:indptr[v + 1]]
            if row.size == 0:
                continue
            k = min(num_neighbor, row.size)
            if prob is None:
                pick = rs.choice(row.size, size=k, replace=False)
            else:
                p = _np.asarray(prob[row], dtype=_np.float64)
                s = p.sum()
                p = p / s if s > 0 else None
                pick = rs.choice(row.size, size=k, replace=False, p=p)
            chosen = []
            for j in sorted(int(i) for i in pick):
                u = int(row[j])
                if u not in layer and len(layer) >= max_num_vertices:
                    continue  # vertex budget exhausted: drop the edge
                chosen.append((u, vals[j]))
                if u not in layer:
                    layer[u] = hop
                    nxt.append(u)
            edges[v] = chosen
        frontier = nxt
    return sorted(layer), layer, edges


def _pack_sample(verts, layer, edges, parent_dtype, max_num_vertices,
                 like, prob=None):
    n = len(verts)
    out_verts = _np.zeros(max_num_vertices + 1, _np.int64)
    out_verts[:n] = verts
    out_verts[-1] = n
    pos = {v: i for i, v in enumerate(verts)}
    rows, cols, vals = [], [], []
    for v in verts:
        for (u, val) in edges.get(v, ()):
            rows.append(pos[v])
            cols.append(u)
            vals.append(val)
    order = _np.lexsort((cols, rows)) if rows else _np.array([], _np.int64)
    rows = _np.asarray(rows, _np.int64)[order]
    cols = _np.asarray(cols, _np.int64)[order]
    vals = _np.asarray(vals, parent_dtype)[order]
    indptr = _np.zeros(max_num_vertices + 1, _np.int64)
    _np.add.at(indptr[1:], rows, 1)
    indptr = _np.cumsum(indptr)
    sub = _mk_csr(vals, indptr, cols,
                  (max_num_vertices, max_num_vertices), like)
    out_layer = _np.full(max_num_vertices, -1, _np.int64)
    out_layer[:n] = [layer[v] for v in verts]
    outs = [_mk_nd(out_verts, like), sub]
    if prob is not None:
        out_prob = _np.zeros(max_num_vertices, _np.float32)
        out_prob[:n] = prob[_np.asarray(verts, _np.int64)]
        outs.append(_mk_nd(out_prob, like))
    outs.append(_mk_nd(out_layer, like))
    return outs


@register("_contrib_dgl_csr_neighbor_uniform_sample",
          aliases=("dgl_csr_neighbor_uniform_sample",), host=True,
          needs_rng=True, num_outputs=-1,
          num_outputs_fn=lambda attrs: 3 * (int(attrs.get("num_args", 2)) - 1))
def dgl_csr_neighbor_uniform_sample(key, csr, *seeds, num_args=None,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100):
    """reference: dgl_graph.cc:744 — per seed array: (vertices, sampled
    csr, layer)."""
    rs = _rng_from_key(key)
    data, indices, indptr, _ = _np_csr(csr)
    outs = [[], [], []]
    for seed in seeds:
        sv = _np.asarray(seed.asnumpy()).astype(_np.int64).ravel()
        verts, layer, edges = _neighbor_sample(
            rs, data, indices, indptr, sv, None, int(num_hops),
            int(num_neighbor), int(max_num_vertices))
        packed = _pack_sample(verts, layer, edges, data.dtype,
                              int(max_num_vertices), csr)
        for o, p in zip(outs, packed):
            o.append(p)
    return tuple(outs[0] + outs[1] + outs[2])


@register("_contrib_dgl_csr_neighbor_non_uniform_sample",
          aliases=("dgl_csr_neighbor_non_uniform_sample",), host=True,
          needs_rng=True, num_outputs=-1,
          num_outputs_fn=lambda attrs: 4 * (int(attrs.get("num_args", 3)) - 2))
def dgl_csr_neighbor_non_uniform_sample(key, csr, prob, *seeds,
                                        num_args=None, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100):
    """reference: dgl_graph.cc:838 — weighted sampling; adds a
    per-vertex probability output set."""
    rs = _rng_from_key(key)
    data, indices, indptr, _ = _np_csr(csr)
    pv = _np.asarray(prob.asnumpy()).astype(_np.float64).ravel()
    outs = [[], [], [], []]
    for seed in seeds:
        sv = _np.asarray(seed.asnumpy()).astype(_np.int64).ravel()
        verts, layer, edges = _neighbor_sample(
            rs, data, indices, indptr, sv, pv, int(num_hops),
            int(num_neighbor), int(max_num_vertices))
        packed = _pack_sample(verts, layer, edges, data.dtype,
                              int(max_num_vertices), csr, prob=pv)
        for o, p in zip(outs, packed):
            o.append(p)
    return tuple(outs[0] + outs[1] + outs[2] + outs[3])


@register("_contrib_dgl_subgraph", aliases=("dgl_subgraph",), host=True,
          num_outputs=-1,
          num_outputs_fn=lambda attrs: (
              (int(attrs.get("num_args", 2)) - 1)
              * (2 if attrs.get("return_mapping") in (True, "True", 1)
                 else 1)))
def dgl_subgraph(graph, *varrays, num_args=None, return_mapping=False):
    """reference: dgl_graph.cc:1115 — vertex-induced subgraph per vertex
    array; with return_mapping the second set holds original edge ids."""
    data, indices, indptr, shape = _np_csr(graph)
    return_mapping = return_mapping in (True, "True", 1)
    new_set, map_set = [], []
    for varr in varrays:
        vids = _np.asarray(varr.asnumpy()).astype(_np.int64).ravel()
        pos = {int(v): i for i, v in enumerate(vids)}
        n = len(vids)
        rows, cols, olds = [], [], []
        for i, v in enumerate(vids):
            for j in range(indptr[v], indptr[v + 1]):
                u = int(indices[j])
                if u in pos:
                    rows.append(i)
                    cols.append(pos[u])
                    olds.append(data[j])
        order = _np.lexsort((cols, rows)) if rows else \
            _np.array([], _np.int64)
        rows = _np.asarray(rows, _np.int64)[order]
        cols = _np.asarray(cols, _np.int64)[order]
        olds = _np.asarray(olds, data.dtype)[order]
        # new edge ids number 1..nnz in row-major order (reference example)
        news = _np.arange(1, len(rows) + 1, dtype=data.dtype)
        indptr_out = _np.zeros(n + 1, _np.int64)
        _np.add.at(indptr_out[1:], rows, 1)
        indptr_out = _np.cumsum(indptr_out)
        new_set.append(_mk_csr(news, indptr_out, cols, (n, n), graph))
        map_set.append(_mk_csr(olds, indptr_out, cols, (n, n), graph))
    return tuple(new_set + map_set) if return_mapping else tuple(new_set)


@register("_contrib_edge_id", aliases=("edge_id",), host=True)
def edge_id(data, u, v):
    """reference: dgl_graph.cc:1300 — out[i] = csr[u[i], v[i]] or -1."""
    d, indices, indptr, _ = _np_csr(data)
    uu = _np.asarray(u.asnumpy()).astype(_np.int64).ravel()
    vv = _np.asarray(v.asnumpy()).astype(_np.int64).ravel()
    out = _np.full(uu.shape, -1, _np.float32)
    for i, (a, b) in enumerate(zip(uu, vv)):
        row = indices[indptr[a]:indptr[a + 1]]
        hit = _np.nonzero(row == b)[0]
        if hit.size:
            out[i] = d[indptr[a] + hit[0]]
    return _mk_nd(out, u)


@register("_contrib_dgl_adjacency", aliases=("dgl_adjacency",), host=True)
def dgl_adjacency(data):
    """reference: dgl_graph.cc:1376 — edge-id csr -> adjacency csr of
    float32 ones."""
    d, indices, indptr, shape = _np_csr(data)
    return _mk_csr(_np.ones(d.shape, _np.float32), indptr, indices, shape,
                   data, dtype=_np.float32)


@register("_contrib_dgl_graph_compact", aliases=("dgl_graph_compact",),
          host=True, num_outputs=-1,
          num_outputs_fn=lambda attrs: (
              (int(attrs.get("num_args", 2)) // 2)
              * (2 if attrs.get("return_mapping") in (True, "True", 1)
                 else 1)))
def dgl_graph_compact(*args, num_args=None, return_mapping=False,
                      graph_sizes=()):
    """reference: dgl_graph.cc:1551 — remove the trailing empty rows and
    map columns from original vertex ids to subgraph positions, using the
    vertex arrays produced by the samplers."""
    return_mapping = return_mapping in (True, "True", 1)
    if isinstance(graph_sizes, (int, float)):
        graph_sizes = (int(graph_sizes),)
    graph_sizes = tuple(int(s) for s in graph_sizes)
    n_graphs = len(args) // 2
    graphs, varrs = args[:n_graphs], args[n_graphs:]
    outs, maps = [], []
    for g, varr, size in zip(graphs, varrs, graph_sizes):
        d, indices, indptr, _ = _np_csr(g)
        verts = _np.asarray(varr.asnumpy()).astype(_np.int64).ravel()
        pos = {int(v): i for i, v in enumerate(verts[:size])}
        rows, cols, vals = [], [], []
        for i in range(size):
            for j in range(indptr[i], indptr[i + 1]):
                u = int(indices[j])
                if u in pos:
                    rows.append(i)
                    cols.append(pos[u])
                    vals.append(d[j])
        order = _np.lexsort((cols, rows)) if rows else \
            _np.array([], _np.int64)
        rows = _np.asarray(rows, _np.int64)[order]
        cols = _np.asarray(cols, _np.int64)[order]
        vals = _np.asarray(vals, d.dtype)[order]
        indptr_out = _np.zeros(size + 1, _np.int64)
        _np.add.at(indptr_out[1:], rows, 1)
        indptr_out = _np.cumsum(indptr_out)
        outs.append(_mk_csr(vals, indptr_out, cols, (size, size), g))
        maps.append(_mk_csr(vals.copy(), indptr_out, cols, (size, size), g))
    return tuple(outs + maps) if return_mapping else tuple(outs)
