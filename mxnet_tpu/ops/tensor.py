"""Tensor ops: elementwise, broadcast, reduction, shape, indexing, init.

TPU-native equivalents of the reference's `src/operator/tensor/` family
(elemwise_unary_op.cc, elemwise_binary_{op,broadcast_op}.cc, matrix_op.cc,
broadcast_reduce_op_value.cc, indexing_op.cc, init_op.cc, ordering_op.cc,
control_flow_op.cc — SURVEY §2.1 N8). Everything is expressed as jnp/lax so
XLA fuses chains of these into single kernels; no hand-written elementwise
kernels needed on TPU.

MXNet semantics preserved where they differ from numpy: `reshape` magic codes
(0/-1/-2/-3/-4, reference: src/operator/tensor/matrix_op-inl.h InferReshapeShape),
`dot` (last-axis • first-axis, src/operator/tensor/dot-inl.h), reductions with
`exclude`, `norm(ord=2)`, topk modes, etc.
"""
from __future__ import annotations

import builtins
import functools

import numpy as _np

from . import register

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _reduce_axes(ndim, axis, exclude=False):
    if axis is None:
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def mx_reshape_shape(ishape, spec, reverse=False):
    """MXNet reshape shape inference with magic codes
    (reference: src/operator/tensor/matrix_op-inl.h:InferReshapeShape)."""
    ishape = tuple(ishape)
    spec = tuple(int(s) for s in spec)
    if reverse:
        rs = mx_reshape_shape(ishape[::-1], spec[::-1], reverse=False)
        return tuple(rs[::-1])
    out = []
    i = 0
    j = 0
    while j < len(spec):
        k = spec[j]
        if k > 0:
            out.append(k)
            i += 1
        elif k == 0:
            out.append(ishape[i])
            i += 1
        elif k == -1:
            out.append(-1)
            i += 1
        elif k == -2:
            out.extend(ishape[i:])
            i = len(ishape)
        elif k == -3:
            out.append(ishape[i] * ishape[i + 1])
            i += 2
        elif k == -4:
            a, b = spec[j + 1], spec[j + 2]
            j += 2
            d = ishape[i]
            i += 1
            if a == -1 and b == -1:
                raise ValueError("reshape -4 cannot infer both factors")
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            out.extend([a, b])
        else:
            raise ValueError("invalid reshape code %d" % k)
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in ishape:
            total *= d
        out[out.index(-1)] = total // builtins.max(known, 1)
    return tuple(out)


def _binary(name, fn):
    register(name)(lambda lhs, rhs: fn(lhs, rhs))
    register("broadcast_" + name.lstrip("_"))(lambda lhs, rhs: fn(lhs, rhs))


# --------------------------------------------------------------------------
# elementwise binary (+ broadcast_ and _scalar variants)
# reference: src/operator/tensor/elemwise_binary_broadcast_op_basic.cc
# --------------------------------------------------------------------------

_BINARY_FNS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: jnp.equal(a, b).astype(jnp.result_type(a, b)),
    "not_equal": lambda a, b: jnp.not_equal(a, b).astype(jnp.result_type(a, b)),
    "greater": lambda a, b: jnp.greater(a, b).astype(jnp.result_type(a, b)),
    "greater_equal": lambda a, b: jnp.greater_equal(a, b).astype(jnp.result_type(a, b)),
    "lesser": lambda a, b: jnp.less(a, b).astype(jnp.result_type(a, b)),
    "lesser_equal": lambda a, b: jnp.less_equal(a, b).astype(jnp.result_type(a, b)),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(jnp.result_type(a, b)),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(jnp.result_type(a, b)),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(jnp.result_type(a, b)),
}

for _n, _f in _BINARY_FNS.items():
    register("elemwise_" + _n, aliases=("_" + _n, "broadcast_" + _n, _n))(
        (lambda f: lambda lhs, rhs: f(lhs, rhs))(_f)
    )

# scalar variants (reference: elemwise_binary_scalar_op_basic.cc)
for _n, _f in _BINARY_FNS.items():
    register("_%s_scalar" % _n)(
        (lambda f: lambda data, scalar=0.0: f(data, jnp.asarray(scalar, dtype=data.dtype)))(_f)
    )

register("_plus_scalar")(lambda data, scalar=0.0: data + jnp.asarray(scalar, data.dtype))
register("_minus_scalar")(lambda data, scalar=0.0: data - jnp.asarray(scalar, data.dtype))
register("_rminus_scalar")(lambda data, scalar=0.0: jnp.asarray(scalar, data.dtype) - data)
register("_mul_scalar")(lambda data, scalar=1.0: data * jnp.asarray(scalar, data.dtype))
register("_div_scalar")(lambda data, scalar=1.0: data / jnp.asarray(scalar, data.dtype))
register("_rdiv_scalar")(lambda data, scalar=1.0: jnp.asarray(scalar, data.dtype) / data)
register("_power_scalar")(lambda data, scalar=1.0: jnp.power(data, jnp.asarray(scalar, data.dtype)))
register("_rpower_scalar")(lambda data, scalar=1.0: jnp.power(jnp.asarray(scalar, data.dtype), data))
register("_mod_scalar")(lambda data, scalar=1.0: jnp.mod(data, jnp.asarray(scalar, data.dtype)))
register("_rmod_scalar")(lambda data, scalar=1.0: jnp.mod(jnp.asarray(scalar, data.dtype), data))


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# --------------------------------------------------------------------------
# elementwise unary (reference: elemwise_unary_op_basic.cc, _trig.cc, _pow.cc)
# --------------------------------------------------------------------------

_UNARY_FNS = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "reciprocal": jnp.reciprocal,
    "negative": jnp.negative,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}

for _n, _f in _UNARY_FNS.items():
    register(_n)((lambda f: lambda data: f(data))(_f))

register("identity", aliases=("_copy",))(lambda data: data)


@register("hard_sigmoid")
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    """reference: src/operator/tensor/elemwise_unary_op_basic.cc HardSigmoid"""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("ravel_multi_index", aliases=("ravel_index",))
def ravel_multi_index(data, shape=()):
    """data: (ndim, N) indices -> (N,) flat ids (reference: ravel.cc)."""
    strides = []
    acc = 1
    for d in reversed(shape):
        strides.append(acc)
        acc *= d
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return (data * strides[:, None]).sum(axis=0)


@register("unravel_index", aliases=("unravel",))
def unravel_index(data, shape=()):
    """(N,) flat ids -> (ndim, N) indices (reference: ravel.cc UnravelIndex)."""
    idx = data.reshape(-1)
    out = []
    for d in reversed(shape):
        out.append(idx % d)
        idx = idx // d
    return jnp.stack(list(reversed(out))).astype(data.dtype)


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    return lax.stop_gradient(data)


@register("Cast", aliases=("cast",))
def cast(data, dtype="float32"):
    from ..base import np_dtype

    return data.astype(np_dtype(dtype))


@register("clip")
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


# --------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# --------------------------------------------------------------------------

def _make_reduce(jfn, name):
    @register(name)
    def _red(data, axis=None, keepdims=False, exclude=False):
        axes = _reduce_axes(data.ndim, axis, exclude)
        if data.ndim == 0:
            return data
        return jfn(data, axis=axes, keepdims=keepdims)

    return _red


_make_reduce(jnp.sum, "sum")
_make_reduce(jnp.mean, "mean")
_make_reduce(jnp.prod, "prod")
_make_reduce(jnp.max, "max")
_make_reduce(jnp.min, "min")
_make_reduce(jnp.nansum, "nansum")
_make_reduce(jnp.nanprod, "nanprod")
register("sum_axis", aliases=("sum_axis",))(lambda data, axis=None, keepdims=False, exclude=False:
                                            jnp.sum(data, axis=_reduce_axes(data.ndim, axis, exclude),
                                                    keepdims=keepdims))


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    axes = None if axis is None else _reduce_axes(data.ndim, axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axes, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=keepdims))


def _arg_index_dtype():
    """Reference argmax/argmin emit float32 positions. float32 is exact only
    to 2^24; in large-tensor mode positions can exceed that, so widen to
    float64 exactly when the shared policy says device ints are int64."""
    from ..base import device_int_dtype

    return jnp.float64 if device_int_dtype() == jnp.int64 else jnp.float32


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(_arg_index_dtype())


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(_arg_index_dtype())


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(_arg_index_dtype())


# --------------------------------------------------------------------------
# dot products (reference: src/operator/tensor/dot-inl.h)
# --------------------------------------------------------------------------

@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXNet dot: contract last axis of lhs with first axis of rhs; full-axis
    transposes applied first. Lowers to a single MXU matmul via reshape."""
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


# --------------------------------------------------------------------------
# shape manipulation (reference: matrix_op.cc)
# --------------------------------------------------------------------------

@register("Reshape", aliases=("reshape",))
def reshape(data, shape=(), reverse=False):
    tgt = mx_reshape_shape(data.shape, shape, reverse)
    return jnp.reshape(data, tgt)


@register("Flatten", aliases=("flatten",))
def flatten_op(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, axes=None):
    if axes is None or len(axes) == 0:
        return jnp.transpose(data)
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("SwapAxis", aliases=("swapaxes",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("reverse", aliases=("flip",))
def reverse(data, axis=0):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=ax)


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("Pad", aliases=("pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register("Concat", aliases=("concat",))
def concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register("SliceChannel", aliases=("split",), num_outputs=-1,
          num_outputs_fn=lambda attrs: int(attrs.get("num_outputs", 1)))
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    slices = []
    for i in range(data.ndim):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if i < len(step) and step[i] is not None and step[i] != 0 else None
        slices.append(builtins.slice(b, e, s))
    return data[tuple(slices)]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=()):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    idx = [builtins.slice(None)] * data.ndim
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("space_to_depth")
def space_to_depth(data, block_size=1, layout="NCHW"):
    """Reference space_to_depth (NCHW, depth order = row-parity-major:
    out channel = a·b·C + ß·C + c). The TPU build adds layout="NHWC"
    (channels-last, same depth order) so the space-to-depth ResNet stem
    works in the MXU-preferred layout without transposes."""
    from .nn import _channels_last

    b = block_size
    if _channels_last(layout):
        n, h, w, c = data.shape
        x = jnp.reshape(data, (n, h // b, b, w // b, b, c))
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return jnp.reshape(x, (n, h // b, w // b, c * b * b))
    n, c, h, w = data.shape
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


# --------------------------------------------------------------------------
# broadcasting (reference: broadcast_reduce_op_value.cc)
# --------------------------------------------------------------------------

@register("broadcast_to")
def broadcast_to(data, shape=()):
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def broadcast_like(data, like):
    return jnp.broadcast_to(data, like.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


# --------------------------------------------------------------------------
# indexing (reference: indexing_op.cc)
# --------------------------------------------------------------------------

from ..base import device_int_dtype as _gather_index_dtype  # gather/scatter
# positions wrap negative past 2^31 under a hard int32 cast; the shared
# helper widens them exactly when large-tensor mode has x64 live

@register("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(_gather_index_dtype())
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
        mode = "clip"
    return jnp.take(a, idx, axis=axis, mode="clip")


@register("batch_take", aliases=("pick",))
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(_gather_index_dtype()), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis % data.ndim), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("Embedding", size_attrs=("input_dim",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32", sparse_grad=False):
    """reference: src/operator/tensor/indexing_op.cc (Embedding). Gather rows
    of `weight`; grad of weight is a scatter-add which XLA emits natively."""
    return jnp.take(weight, data.astype(_gather_index_dtype()), axis=0, mode="clip")


@register("one_hot", size_attrs=("depth",))
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import np_dtype

    oh = jax.nn.one_hot(indices.astype(_gather_index_dtype()), depth)
    return (oh * (on_value - off_value) + off_value).astype(np_dtype(dtype))


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(_gather_index_dtype()))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=()):
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices.astype(_gather_index_dtype()))
    return out.at[idx].set(data)


@register("_scatter_set_nd")
def scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(indices.astype(_gather_index_dtype()))
    return lhs.at[idx].set(rhs)


@register("where")
def where(condition, x, y):
    if condition.shape != x.shape and condition.ndim == 1:
        cond = condition.reshape((-1,) + (1,) * (x.ndim - 1)).astype(bool)
    else:
        cond = condition.astype(bool)
    return jnp.where(cond, x, y)


# --------------------------------------------------------------------------
# ordering (reference: ordering_op.cc)
# --------------------------------------------------------------------------

@register("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import np_dtype

    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(np_dtype(dtype))


@register("topk", num_outputs=-1)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import np_dtype

    ax = axis % data.ndim
    moved = jnp.moveaxis(data, ax, -1)
    vals, idxs = lax.top_k(jnp.negative(moved) if is_ascend else moved, k)
    if is_ascend:
        vals = jnp.negative(vals)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax).astype(np_dtype(dtype))
    if ret_typ == "value":
        return (vals,)
    if ret_typ == "both":
        return (vals, idxs)
    if ret_typ == "mask":
        mask = jnp.zeros(moved.shape, data.dtype)
        mask = jax.vmap(lambda m, i: m.at[i].set(1), in_axes=(0, 0))(
            mask.reshape((-1, moved.shape[-1])),
            jnp.moveaxis(data, ax, -1).reshape((-1, moved.shape[-1])).argsort(-1)[:, -k:]
            if not is_ascend
            else jnp.moveaxis(data, ax, -1).reshape((-1, moved.shape[-1])).argsort(-1)[:, :k],
        ).reshape(moved.shape)
        return (jnp.moveaxis(mask, -1, ax),)
    return (idxs,)


# --------------------------------------------------------------------------
# init / creation ops (reference: init_op.cc)
# --------------------------------------------------------------------------

@register("shape_array")
def shape_array(data):
    # reference emits int64; see base.device_int_dtype for the policy
    return jnp.asarray(data.shape, dtype=_gather_index_dtype())


@register("size_array")
def size_array(data):
    return jnp.asarray([data.size], dtype=_gather_index_dtype())


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("_zeros")
def _zeros(shape=(), dtype="float32"):
    from ..base import np_dtype

    return jnp.zeros(shape, np_dtype(dtype))


@register("_ones")
def _ones(shape=(), dtype="float32"):
    from ..base import np_dtype

    return jnp.ones(shape, np_dtype(dtype))


@register("_full")
def _full(shape=(), value=0.0, dtype="float32"):
    from ..base import np_dtype

    return jnp.full(shape, value, np_dtype(dtype))


@register("_arange", size_attrs=("start", "stop"))
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    from ..base import np_dtype

    out = jnp.arange(start, stop, step, np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace")
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    from ..base import np_dtype

    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=np_dtype(dtype))


@register("_eye")
def _eye(N=0, M=0, k=0, dtype="float32"):
    from ..base import np_dtype

    return jnp.eye(N, M if M else None, k, np_dtype(dtype))


@register("diag")
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


# --------------------------------------------------------------------------
# sequence ops (reference: src/operator/sequence_{mask,last,reverse}.cc)
# layout: (seq_len, batch, ...) when use_sequence_length
# --------------------------------------------------------------------------

@register("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    seq_axis = axis
    batch_axis = 1 - axis
    steps = jnp.arange(data.shape[seq_axis])
    mask = steps[:, None] < sequence_length[None, :].astype(steps.dtype)
    if seq_axis == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [builtins.slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)
    return jax.vmap(lambda x, i: x[i], in_axes=(1, 0))(moved, last)


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    T = moved.shape[0]

    def rev_one(x, L):
        idx = jnp.where(jnp.arange(T) < L, L - 1 - jnp.arange(T), jnp.arange(T))
        return x[idx]

    out = jax.vmap(rev_one, in_axes=(1, 0), out_axes=1)(moved, sequence_length.astype(jnp.int32))
    return jnp.moveaxis(out, 0, axis)


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------

@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2, 0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape((-1,) + out.shape[1:])
    return out


@register("histogram", num_outputs=2)
def histogram(data, bin_cnt=10, range=None):
    flat = data.reshape(-1)
    if range is not None:
        lo = jnp.asarray(range[0], flat.dtype)
        hi = jnp.asarray(range[1], flat.dtype)
    else:
        lo, hi = flat.min(), flat.max()
    edges = lo + (hi - lo) * jnp.arange(bin_cnt + 1, dtype=flat.dtype) / bin_cnt
    scaled = (flat - lo) / jnp.maximum(hi - lo, jnp.asarray(1e-12, flat.dtype)) * bin_cnt
    idx = jnp.clip(scaled.astype(jnp.int32), 0, bin_cnt - 1)
    # int32 counts (int64 policy, README divergences): the reference emits
    # int64, but device integers are int32 under default JAX config and
    # requesting int64 here only produced a truncation warning per call
    cnt = jnp.zeros((bin_cnt,), jnp.int32).at[idx].add(1)
    return cnt, edges
