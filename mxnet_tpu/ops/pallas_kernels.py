"""Pallas TPU kernels.

The reference ships hand-written CUDA where library kernels fall short
(src/operator/contrib/transformer.cu, fused RNN rnn-inl.h); the TPU-native
equivalent is Pallas. This module holds the kernels where XLA fusion alone
is insufficient — flash attention first: XLA materializes the (Lq, Lk)
score matrix in HBM, while the flash kernel streams K/V blocks through VMEM
with an online softmax, keeping the working set on-chip (HBM traffic
O(L·D) instead of O(L²)).

On non-TPU backends the same kernels run in interpret mode, so tests and
CPU development use one code path (the strategy SURVEY §4 prescribes for
cross-backend consistency).

Backward: Pallas kernels too (flash-attention backward): the forward saves
only O and the per-row logsumexp; backward recomputes P blockwise in VMEM —
one kernel accumulating dQ over k-blocks, one accumulating dK/dV over
q-blocks — so the backward pass has the same O(L·D) HBM traffic as forward
instead of materializing the (Lq, Lk) probability matrix.
"""
from __future__ import annotations

import functools

import numpy as _np

__all__ = ["flash_attention", "lstm_layer", "conv_epilogue",
           "conv_epilogue_fits", "paged_attention",
           "paged_attention_reference"]

_NEG_INF = -1e30


def _use_interpret():
    import jax

    return jax.default_backend() != "tpu"


def _attention_reference(q, k, v, causal, sm_scale):
    """Plain jnp attention (the vjp source for backward; also the numerics
    oracle in tests)."""
    import jax.numpy as jnp

    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        row = jnp.arange(lq)[:, None]
        col = jnp.arange(lk)[None, :]
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                lq, lk, block_q, block_k, n_kblocks):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (block_q, D)
    d = q.shape[-1]

    row_ids = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        col_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col_ids < lk
        if causal:
            mask = jnp.logical_and(mask, col_ids <= row_ids)
        s = jnp.where(mask, s, _NEG_INF)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, l, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    # causal: k-blocks strictly above this q-block's diagonal contribute
    # nothing — skip them (dynamic fori bound lowers to while_loop)
    hi = n_kblocks if not causal else jnp.minimum(
        n_kblocks, ((iq + 1) * block_q + block_k - 1) // block_k)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # logsumexp per q row — the only softmax state backward needs. Stored
    # (bh, 8, lq): TPU blocks need sublane-dim multiples of 8, so the row
    # vector is broadcast across 8 sublanes rather than stored (bh, lq).
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]
    lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


@functools.lru_cache(maxsize=256)
def _fwd_compiled(shape_key):
    (bh, lq, lk, d, dtype, causal, sm_scale, interpret) = shape_key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q = min(128, lq)
    block_k = min(128, lk)
    n_q = -(-lq // block_q)
    n_k = -(-lk // block_k)
    lq_pad, lk_pad = n_q * block_q, n_k * block_k

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               lq=lq, lk=lk, block_q=block_q, block_k=block_k,
                               n_kblocks=n_k)

    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, lq_pad, d), _np.dtype(dtype)),
                   jax.ShapeDtypeStruct((bh, 8, lq_pad), _np.float32)),
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )

    def run(q, k, v):
        qp = jnp.pad(q, ((0, 0), (0, lq_pad - lq), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, lk_pad - lk), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, lk_pad - lk), (0, 0)))
        o, lse = call(qp, kp, vp)
        return o[:, :lq, :], lse[:, 0, :lq]

    return run


def _flash_fwd(q, k, v, causal, sm_scale):
    bh, lq, d = q.shape
    lk = k.shape[1]
    run = _fwd_compiled((bh, lq, lk, d, str(q.dtype), bool(causal),
                         float(sm_scale), _use_interpret()))
    return run(q, k, v)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   sm_scale, causal, lk, block_q, block_k, n_kblocks):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                     # (bq, d)
    do = do_ref[0].astype(jnp.float32)                   # (bq, d)
    lse = lse_ref[0, 0][:, None]                         # (bq, 1)
    delta = delta_ref[0, 0][:, None]                     # (bq, 1)
    d = q.shape[-1]
    row_ids = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, acc):
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        col_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col_ids < lk
        if causal:
            mask = jnp.logical_and(mask, col_ids <= row_ids)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return acc + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    hi = n_kblocks if not causal else jnp.minimum(
        n_kblocks, ((iq + 1) * block_q + block_k - 1) // block_k)
    dq_ref[0] = jax.lax.fori_loop(0, hi, body, acc0).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, lq, lk, block_q,
                    block_k, n_qblocks):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # (bk, d)
    v = v_ref[0].astype(jnp.float32)                     # (bk, d)
    d = k.shape[-1]
    col_ids = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        row_ids = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        # mask padded q rows too: their lse is garbage and exp could
        # overflow — dO=0 alone doesn't save p itself
        mask = jnp.logical_and(col_ids < lk, row_ids < lq)
        if causal:
            mask = jnp.logical_and(mask, col_ids <= row_ids)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, d), jnp.float32)
    lo = 0 if not causal else (ik * block_k) // block_q
    dk, dv = jax.lax.fori_loop(lo, n_qblocks, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.lru_cache(maxsize=256)
def _bwd_compiled(shape_key):
    (bh, lq, lk, d, dtype, causal, sm_scale, interpret) = shape_key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q = min(128, lq)
    block_k = min(128, lk)
    n_q = -(-lq // block_q)
    n_k = -(-lk // block_k)
    lq_pad, lk_pad = n_q * block_q, n_k * block_k

    dq_call = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          lk=lk, block_q=block_q, block_k=block_k,
                          n_kblocks=n_k),
        out_shape=jax.ShapeDtypeStruct((bh, lq_pad, d), _np.dtype(dtype)),
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),     # q
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # k
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # v
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),     # do
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i),
                         memory_space=pltpu.VMEM),     # lse
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i),
                         memory_space=pltpu.VMEM),     # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )

    dkv_call = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          lq=lq, lk=lk, block_q=block_q, block_k=block_k,
                          n_qblocks=n_q),
        out_shape=(jax.ShapeDtypeStruct((bh, lk_pad, d), _np.dtype(dtype)),
                   jax.ShapeDtypeStruct((bh, lk_pad, d), _np.dtype(dtype))),
        grid=(bh, n_k),
        in_specs=[
            pl.BlockSpec((1, lq_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # q
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),     # k
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),     # v
            pl.BlockSpec((1, lq_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # do
            pl.BlockSpec((1, 8, lq_pad), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # lse
            pl.BlockSpec((1, 8, lq_pad), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),     # delta
        ],
        out_specs=(pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )

    def run(q, k, v, o, lse, do):
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                        # (bh, lq)
        qp = jnp.pad(q, ((0, 0), (0, lq_pad - lq), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, lk_pad - lk), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, lk_pad - lk), (0, 0)))
        dop = jnp.pad(do, ((0, 0), (0, lq_pad - lq), (0, 0)))
        lsep = jnp.broadcast_to(
            jnp.pad(lse, ((0, 0), (0, lq_pad - lq)))[:, None, :],
            (bh, 8, lq_pad))
        deltap = jnp.broadcast_to(
            jnp.pad(delta, ((0, 0), (0, lq_pad - lq)))[:, None, :],
            (bh, 8, lq_pad))
        dq = dq_call(qp, kp, vp, dop, lsep, deltap)
        dk, dv = dkv_call(qp, kp, vp, dop, lsep, deltap)
        return (dq[:, :lq, :], dk[:, :lk, :], dv[:, :lk, :])

    return run


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Flash attention over (..., L, D) tensors (leading dims are batched).

    TPU-native replacement for attention assembled from the reference's
    primitive ops (batch_dot + softmax + batch_dot, e.g.
    src/operator/contrib/transformer.cc usage); same math, O(L·D) HBM
    traffic. Differentiable via recompute-vjp.
    """
    import jax
    import jax.numpy as jnp

    if sm_scale is None:
        sm_scale = 1.0 / float(_np.sqrt(q.shape[-1]))
    sm_scale = float(sm_scale)

    lead = q.shape[:-2]
    lq, d = q.shape[-2:]
    lk = k.shape[-2]
    qf = q.reshape((-1, lq, d))
    kf = k.reshape((-1, lk, d))
    vf = v.reshape((-1, lk, d))

    @jax.custom_vjp
    def attn(qf, kf, vf):
        return _flash_fwd(qf, kf, vf, causal, sm_scale)[0]

    def fwd(qf, kf, vf):
        o, lse = _flash_fwd(qf, kf, vf, causal, sm_scale)
        return o, (qf, kf, vf, o, lse)

    def bwd(res, g):
        qf, kf, vf, o, lse = res
        bh, lq_, d_ = qf.shape
        lk_ = kf.shape[1]
        run = _bwd_compiled((bh, lq_, lk_, d_, str(qf.dtype), bool(causal),
                             float(sm_scale), _use_interpret()))
        return run(qf, kf, vf, o, lse, g.astype(qf.dtype))

    attn.defvjp(fwd, bwd)
    return attn(qf, kf, vf).reshape(lead + (lq, d))


# ---------------------------------------------------------------------------
# Fused LSTM layer: the whole time loop in ONE kernel, recurrent weights
# resident in VMEM.
#
# TPU-native replacement for the reference's fused cuDNN RNN kernel
# (src/operator/rnn-inl.h:162, cudnn_rnn-inl.h). A lax.scan LSTM issues one
# tiny h2h matmul per timestep; at word-LM shapes (B=32, H=650) each step
# re-reads the 3.4 MB recurrent weight from HBM and leaves the MXU ~95%
# idle (measured 5.3% MFU, BENCH_local_r04_lstm). Here the grid is the time
# axis (sequential on TPU), w_hh stays in VMEM across all steps, and the
# h/c carries live in f32 VMEM scratch — per-step HBM traffic drops to the
# gx slice in + (y, c, gates) slices out.
#
# Backward is a second Pallas kernel running the time grid in reverse,
# producing per-step pre-activation gate grads (dgx); the weight gradient
# dW_hh = h_prevᵀ·dgx then falls out as ONE large MXU matmul outside the
# kernel instead of T tiny accumulations.
# ---------------------------------------------------------------------------


def lstm_layer_fits(b, h, itemsize):
    """Conservative VMEM budget check for the fused LSTM kernels: w_hhᵀ must
    stay resident plus double-buffered per-step blocks and the f32 carries.
    Budgets against max(forward, backward) per-step traffic — training runs
    BOTH kernels, and for bf16 the backward's per-step blocks are slightly
    larger (dy + gates + c_t + c_prev in, dgx out), so a forward-only check
    could admit a shape that then fails to compile in the backward pass.
    Callers fall back to the lax.scan path when this returns False (large-H
    models that fit fine under scan must not start failing to compile)."""
    hp = -(-h // 128) * 128
    bp = -(-b // 16) * 16
    resident = hp * 4 * hp * itemsize          # w_hhᵀ
    resident += 2 * bp * hp * 4                # f32 h/c (dh/dc) scratch
    fwd_step = bp * 4 * hp * itemsize * 2      # gx in + gates out
    fwd_step += bp * hp * (2 * itemsize + 4)   # ys out + c_all out (f32)
    bwd_step = bp * 4 * hp * itemsize * 2      # gates in + dgx out
    bwd_step += bp * hp * itemsize             # dy in
    bwd_step += 2 * bp * hp * 4                # c_t + c_{t-1} in (f32)
    per_step = max(fwd_step, bwd_step)
    return resident + 2 * per_step < 12 * 1024 * 1024


def _pad_gate_cols(a, h, hp, gates=4):
    """Pad each of the `gates` H-sized blocks along the last axis to Hp."""
    import jax.numpy as jnp

    if h == hp:
        return a
    pads = [(0, 0)] * (a.ndim - 1) + [(0, hp - h)]
    return jnp.concatenate(
        [jnp.pad(p, pads) for p in jnp.split(a, gates, axis=-1)], axis=-1)


def _lstm_fwd_kernel(gx_ref, wht_ref, h0_ref, c0_ref,
                     ys_ref, c_ref, gates_ref, h_scr, c_scr, *, hp):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    h = h_scr[...]
    c = c_scr[...]
    # recurrent matmul in the input dtype (bf16 hits the MXU fast path);
    # carries stay f32 for accumulation accuracy
    g = gx_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h.astype(gx_ref.dtype), wht_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(g[:, :hp])
    f = jax.nn.sigmoid(g[:, hp:2 * hp])
    gg = jnp.tanh(g[:, 2 * hp:3 * hp])
    o = jax.nn.sigmoid(g[:, 3 * hp:])
    c_new = f * c + i * gg
    h_new = o * jnp.tanh(c_new)
    ys_ref[0] = h_new.astype(ys_ref.dtype)
    c_ref[0] = c_new
    gates_ref[0] = jnp.concatenate([i, f, gg, o], axis=-1).astype(
        gates_ref.dtype)
    h_scr[...] = h_new
    c_scr[...] = c_new


def _lstm_bwd_kernel(dy_ref, gates_ref, c_ref, cprev_ref, c0_ref, dct_ref,
                     wht_ref, dgx_ref, dh0_ref, dc0_ref, dh_scr, dc_scr,
                     *, nt, hp):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rt = pl.program_id(0)          # reverse step: t = nt - 1 - rt

    @pl.when(rt == 0)
    def _():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = dct_ref[...].astype(jnp.float32)

    ga = gates_ref[0].astype(jnp.float32)
    i, f = ga[:, :hp], ga[:, hp:2 * hp]
    gg, o = ga[:, 2 * hp:3 * hp], ga[:, 3 * hp:]
    c_t = c_ref[0]
    c_prev = jnp.where(rt == nt - 1, c0_ref[...].astype(jnp.float32),
                       cprev_ref[0])
    dh = dy_ref[0].astype(jnp.float32) + dh_scr[...]
    tc = jnp.tanh(c_t)
    do = dh * tc
    dc = dc_scr[...] + dh * o * (1.0 - tc * tc)
    dgates = jnp.concatenate([
        (dc * gg) * i * (1.0 - i),           # d(pre-i)
        (dc * c_prev) * f * (1.0 - f),       # d(pre-f)
        (dc * i) * (1.0 - gg * gg),          # d(pre-g)
        do * o * (1.0 - o),                  # d(pre-o)
    ], axis=-1).astype(dgx_ref.dtype)
    dgx_ref[0] = dgates
    dh_new = jax.lax.dot_general(
        dgates, wht_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dc_new = dc * f
    dh_scr[...] = dh_new
    dc_scr[...] = dc_new
    # constant-indexed output block: every step overwrites, the final grid
    # step (t == 0) leaves the real dh0/dc0
    dh0_ref[...] = dh_new.astype(dh0_ref.dtype)
    dc0_ref[...] = dc_new.astype(dc0_ref.dtype)


def _lstm_infer_kernel(gx_ref, wht_ref, h0_ref, c0_ref, ys_ref, ct_ref,
                       h_scr, c_scr, *, hp):
    """Residual-free forward (inference): only ys and the final c leave the
    kernel — no gates/c_all saves, so the primal path pays no training-
    residual HBM writes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    h = h_scr[...]
    c = c_scr[...]
    g = gx_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h.astype(gx_ref.dtype), wht_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    i = jax.nn.sigmoid(g[:, :hp])
    f = jax.nn.sigmoid(g[:, hp:2 * hp])
    gg = jnp.tanh(g[:, 2 * hp:3 * hp])
    o = jax.nn.sigmoid(g[:, 3 * hp:])
    c_new = f * c + i * gg
    h_new = o * jnp.tanh(c_new)
    ys_ref[0] = h_new.astype(ys_ref.dtype)
    h_scr[...] = h_new
    c_scr[...] = c_new
    # constant-indexed: last grid step leaves cT
    ct_ref[...] = c_new.astype(ct_ref.dtype)


@functools.lru_cache(maxsize=64)
def _lstm_infer_compiled(key):
    nt, bp, hp, dtype, interpret = key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        functools.partial(_lstm_infer_kernel, hp=hp),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, bp, 4 * hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((hp, 4 * hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((nt, bp, hp), _np.dtype(dtype)),
            jax.ShapeDtypeStruct((bp, hp), _np.dtype(dtype)),
        ),
        out_specs=(
            pl.BlockSpec((1, bp, hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((bp, hp), jnp.float32),
                        pltpu.VMEM((bp, hp), jnp.float32)],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=64)
def _lstm_fwd_compiled(key):
    nt, bp, hp, dtype, interpret = key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        functools.partial(_lstm_fwd_kernel, hp=hp),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, bp, 4 * hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),          # gx
            pl.BlockSpec((hp, 4 * hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),          # w_hhᵀ (resident)
            pl.BlockSpec((bp, hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),          # h0
            pl.BlockSpec((bp, hp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),          # c0
        ],
        out_shape=(
            jax.ShapeDtypeStruct((nt, bp, hp), _np.dtype(dtype)),    # ys
            jax.ShapeDtypeStruct((nt, bp, hp), _np.float32),         # c_t
            jax.ShapeDtypeStruct((nt, bp, 4 * hp), _np.dtype(dtype)),  # gates
        ),
        out_specs=(
            pl.BlockSpec((1, bp, hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bp, hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bp, 4 * hp), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((bp, hp), jnp.float32),
                        pltpu.VMEM((bp, hp), jnp.float32)],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=64)
def _lstm_bwd_compiled(key):
    nt, bp, hp, dtype, interpret = key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rev = lambda rt: (nt - 1 - rt, 0, 0)
    return pl.pallas_call(
        functools.partial(_lstm_bwd_kernel, nt=nt, hp=hp),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, bp, hp), rev, memory_space=pltpu.VMEM),    # dy
            pl.BlockSpec((1, bp, 4 * hp), rev,
                         memory_space=pltpu.VMEM),                      # gates
            pl.BlockSpec((1, bp, hp), rev, memory_space=pltpu.VMEM),    # c_t
            pl.BlockSpec((1, bp, hp),
                         lambda rt: (jnp.maximum(nt - 2 - rt, 0), 0, 0),
                         memory_space=pltpu.VMEM),                      # c_{t-1}
            pl.BlockSpec((bp, hp), lambda rt: (0, 0),
                         memory_space=pltpu.VMEM),                      # c0
            pl.BlockSpec((bp, hp), lambda rt: (0, 0),
                         memory_space=pltpu.VMEM),                      # dcT
            pl.BlockSpec((hp, 4 * hp), lambda rt: (0, 0),
                         memory_space=pltpu.VMEM),                      # w_hhᵀ
        ],
        out_shape=(
            jax.ShapeDtypeStruct((nt, bp, 4 * hp), _np.dtype(dtype)),  # dgx
            jax.ShapeDtypeStruct((bp, hp), _np.dtype(dtype)),          # dh0
            jax.ShapeDtypeStruct((bp, hp), _np.dtype(dtype)),          # dc0
        ),
        out_specs=(
            pl.BlockSpec((1, bp, 4 * hp), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, hp), lambda rt: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, hp), lambda rt: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((bp, hp), jnp.float32),
                        pltpu.VMEM((bp, hp), jnp.float32)],
        interpret=interpret,
    )


def lstm_layer(gx, wh, h0, c0):
    """One LSTM layer over a precomputed input projection.

    gx: (T, B, 4H) = x·w_ihᵀ + b_ih + b_hh (both biases folded — they are
    additive in the LSTM cell). wh: (4H, H) recurrent weight in the
    reference's flat layout (gate order i, f, g, o — rnn-inl.h). h0/c0:
    (B, H). Returns (ys (T,B,H), hT, cT). Differentiable via a Pallas
    backward kernel; dW_hh reduces to one large matmul outside the kernel.
    """
    import jax
    import jax.numpy as jnp

    nt, b, gh = gx.shape
    h = gh // 4
    hp = -(-h // 128) * 128
    bp = -(-b // 16) * 16
    dtype = gx.dtype
    interpret = _use_interpret()

    # w_hhᵀ padded to (Hp, 4Hp): pad the H rows, then each gate col block
    wht = _pad_gate_cols(jnp.pad(wh.T, ((0, hp - h), (0, 0))), h, hp)
    gx_p = _pad_gate_cols(
        jnp.pad(gx, ((0, 0), (0, bp - b), (0, 0))), h, hp)
    h0_p = jnp.pad(h0, ((0, bp - b), (0, hp - h)))
    c0_p = jnp.pad(c0, ((0, bp - b), (0, hp - h)))

    @jax.custom_vjp
    def scan_p(gx_p, wht, h0_p, c0_p):
        # primal (not being differentiated): residual-free kernel
        return _lstm_infer_compiled(
            (nt, bp, hp, str(dtype), interpret))(gx_p, wht, h0_p, c0_p)

    def fwd(gx_p, wht, h0_p, c0_p):
        ys_p, c_all, gates = _lstm_fwd_compiled(
            (nt, bp, hp, str(dtype), interpret))(gx_p, wht, h0_p, c0_p)
        return (ys_p, c_all[-1].astype(dtype)), \
            (wht, gates, c_all, h0_p, c0_p, ys_p)

    def bwd(res, cts):
        wht, gates, c_all, h0_p, c0_p, ys_p = res
        dys_p, dct_p = cts
        dgx_p, dh0_p, dc0_p = _lstm_bwd_compiled(
            (nt, bp, hp, str(dtype), interpret))(
            dys_p.astype(dtype), gates, c_all, c_all, c0_p,
            dct_p.astype(dtype), wht)
        # dW_hhᵀ = Σ_t h_{t-1}ᵀ · dgates_t — one large MXU matmul
        h_prev = jnp.concatenate([h0_p[None], ys_p[:-1]], axis=0)
        dwht = jax.lax.dot_general(
            h_prev.reshape(-1, hp), dgx_p.reshape(-1, 4 * hp),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(wht.dtype)
        return dgx_p, dwht, dh0_p, dc0_p

    scan_p.defvjp(fwd, bwd)
    ys_p, ct_p = scan_p(gx_p, wht, h0_p, c0_p)
    ys = ys_p[:, :b, :h]
    return ys, ys[-1], ct_p[:b, :h]


# ---------------------------------------------------------------------------
# Fused conv-epilogue: BN batch-stats + normalize + ReLU (+ residual add) as
# TWO Pallas passes over the activation instead of the unfused graph's four+.
#
# The round-4 profile (docs/perf_notes.md) showed the bs256 ResNet-50 train
# step is HBM-bound on the elementwise traffic AROUND the convolutions
# (~67 GB/step after the BN custom-vjp): separate stats, normalize, ReLU and
# residual-add each re-read/re-write the full activation. Here the epilogue
# of a conv is exactly two activation-sized passes:
#
#   pass 1 (stats):   read x           -> per-channel Σd, Σd² (f32, on-chip)
#   pass 2 (apply):   read x (+res)    -> write act(x·scale + shift (+res))
#
# and the backward is likewise two passes (channel reductions, then dx/dres).
# The layout is channels-last (the MXU-preferred layout the NHWC bench path
# uses): the activation flattens to (R=N·H·W, C) with NO data movement, the
# grid walks row blocks, and the per-channel vectors ride (8, Cp) f32 blocks
# exactly like the flash kernels' lse rows. Channels-first callers use the
# pure-jnp fallback (ops/nn.py) — a transpose would cost the very HBM pass
# this kernel exists to remove.
#
# Stats use the same proxy-shifted single-read moments as ops/nn.py
# _bn_stats: d = x - proxy keeps E[d²]-E[d]² from cancelling for
# large-mean/small-spread channels; all accumulation is f32.
# ---------------------------------------------------------------------------


def conv_epilogue_fits(c, itemsize):
    """VMEM budget check for the fused conv-epilogue kernels. The row-block
    size shrinks as C grows (see _epi_rows), so this only rejects channel
    widths whose single-row tiles cannot fit; callers fall back to the
    pure-jnp path when this returns False."""
    cp = -(-c // 128) * 128
    rb = _epi_rows(cp)
    # worst kernel (backward dx with residual): ~3 input-dtype row blocks
    # streamed (ct, x, out) + 2 written (dx, dres) + ~2 f32 temporaries in
    # flight, plus the 8-row f32 channel vectors
    blocks = rb * cp * (5 * itemsize + 2 * 4)
    return (blocks + 6 * 8 * cp * 4) < 12 * 1024 * 1024


def _epi_rows(cp):
    """Row-block size: ~2 MB f32 per (rb, Cp) block, 32-row multiples (covers
    the bf16 16-sublane tile), floor 32."""
    rb = (2 * 1024 * 1024) // (cp * 4)
    return max(32, min(512, (rb // 32) * 32))


def _epi_stats_kernel(x_ref, proxy_ref, s1_ref, s2_ref, *, rb, r):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    x = x_ref[...].astype(jnp.float32)                 # (rb, Cp)
    rows = i * rb + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    d = jnp.where(rows < r, x - proxy_ref[0:1, :], 0.0)
    s1 = jnp.sum(d, axis=0, keepdims=True)             # (1, Cp)
    s2 = jnp.sum(d * d, axis=0, keepdims=True)
    s1_ref[...] = s1_ref[...] + jnp.broadcast_to(s1, s1_ref.shape)
    s2_ref[...] = s2_ref[...] + jnp.broadcast_to(s2, s2_ref.shape)


def _epi_apply_kernel(*refs, relu, has_res):
    import jax.numpy as jnp

    if has_res:
        x_ref, res_ref, scale_ref, shift_ref, out_ref = refs
    else:
        x_ref, scale_ref, shift_ref, out_ref = refs
        res_ref = None
    y = (x_ref[...].astype(jnp.float32) * scale_ref[0:1, :]
         + shift_ref[0:1, :])
    if res_ref is not None:
        y = y + res_ref[...].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    out_ref[...] = y.astype(out_ref.dtype)


def _epi_bwd_reduce_kernel(*refs, rb, r, relu):
    """Per-channel Σg and Σg·x̂ where g = ct·[out>0] (ReLU mask) — the two
    reductions every BN backward needs, in ONE read of (ct, x[, out]).
    Without relu the saved `out` is neither streamed nor read."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if relu:
        ct_ref, x_ref, out_ref, mean_ref, inv_ref, db_ref, dg_ref = refs
    else:
        ct_ref, x_ref, mean_ref, inv_ref, db_ref, dg_ref = refs
        out_ref = None
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        db_ref[...] = jnp.zeros_like(db_ref)
        dg_ref[...] = jnp.zeros_like(dg_ref)

    g = ct_ref[...].astype(jnp.float32)
    if relu:
        g = jnp.where(out_ref[...].astype(jnp.float32) > 0.0, g, 0.0)
    rows = i * rb + jax.lax.broadcasted_iota(jnp.int32, g.shape, 0)
    g = jnp.where(rows < r, g, 0.0)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[0:1, :]) \
        * inv_ref[0:1, :]
    db = jnp.sum(g, axis=0, keepdims=True)
    dg = jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[...] = db_ref[...] + jnp.broadcast_to(db, db_ref.shape)
    dg_ref[...] = dg_ref[...] + jnp.broadcast_to(dg, dg_ref.shape)


def _epi_bwd_dx_kernel(*refs, relu, has_res):
    """dx = (γ·inv)·(g - Σg/R - x̂·Σ(g·x̂)/R), dres = g — one read of
    (ct, x[, out]), one write of dx (+dres)."""
    import jax.numpy as jnp

    refs = list(refs)
    ct_ref, x_ref = refs[0], refs[1]
    out_ref = refs[2] if relu else None
    k = 3 if relu else 2
    mean_ref, inv_ref, coef_ref, cb_ref, cg_ref = refs[k:k + 5]
    dx_ref = refs[k + 5]
    dres_ref = refs[k + 6] if has_res else None
    g = ct_ref[...].astype(jnp.float32)
    if relu:
        g = jnp.where(out_ref[...].astype(jnp.float32) > 0.0, g, 0.0)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[0:1, :]) \
        * inv_ref[0:1, :]
    dx = coef_ref[0:1, :] * (g - cb_ref[0:1, :] - xhat * cg_ref[0:1, :])
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if dres_ref is not None:
        dres_ref[...] = g.astype(dres_ref.dtype)


def _vec8(v, cp):
    """Per-channel f32 vector -> (8, Cp) block (TPU sublane-dim minimum)."""
    import jax.numpy as jnp

    v = jnp.pad(v.astype(jnp.float32), (0, cp - v.shape[0]))
    return jnp.broadcast_to(v[None, :], (8, cp))


def _epi_geom(r, c):
    cp = -(-c // 128) * 128
    rb = _epi_rows(cp)
    n_blocks = -(-r // rb)
    return rb, cp, n_blocks, n_blocks * rb


def _epi_specs(r, c):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rb, cp, n_blocks, rp = _epi_geom(r, c)
    row_spec = pl.BlockSpec((rb, cp), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((8, cp), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    vec_shape = jax.ShapeDtypeStruct((8, cp), _np.float32)
    return rb, cp, n_blocks, rp, row_spec, vec_spec, vec_shape


# the four pallas_calls are cached SEPARATELY on exactly the parameters
# each kernel depends on: the returned callables are stable objects, so
# jax's trace cache reuses e.g. one stats executable across every
# (relu, has_res) epilogue variant of the same shape

@functools.lru_cache(maxsize=256)
def _epi_stats_compiled(key):
    (r, c, dtype, interpret) = key
    from jax.experimental import pallas as pl

    rb, cp, n_blocks, rp, row_spec, vec_spec, vec_shape = _epi_specs(r, c)
    return pl.pallas_call(
        functools.partial(_epi_stats_kernel, rb=rb, r=r),
        grid=(n_blocks,),
        in_specs=[row_spec, vec_spec],
        out_shape=(vec_shape, vec_shape),
        out_specs=(vec_spec, vec_spec),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _epi_apply_compiled(key):
    (r, c, dtype, relu, has_res, interpret) = key
    import jax
    from jax.experimental import pallas as pl

    rb, cp, n_blocks, rp, row_spec, vec_spec, _ = _epi_specs(r, c)
    return pl.pallas_call(
        functools.partial(_epi_apply_kernel, relu=relu, has_res=has_res),
        grid=(n_blocks,),
        in_specs=[row_spec] * (2 if has_res else 1) + [vec_spec, vec_spec],
        out_shape=jax.ShapeDtypeStruct((rp, cp), _np.dtype(dtype)),
        out_specs=row_spec,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _epi_reduce_compiled(key):
    (r, c, dtype, relu, interpret) = key
    from jax.experimental import pallas as pl

    rb, cp, n_blocks, rp, row_spec, vec_spec, vec_shape = _epi_specs(r, c)
    return pl.pallas_call(
        functools.partial(_epi_bwd_reduce_kernel, rb=rb, r=r, relu=relu),
        grid=(n_blocks,),
        in_specs=[row_spec] * (3 if relu else 2) + [vec_spec, vec_spec],
        out_shape=(vec_shape, vec_shape),
        out_specs=(vec_spec, vec_spec),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _epi_dx_compiled(key):
    (r, c, dtype, relu, has_res, interpret) = key
    import jax
    from jax.experimental import pallas as pl

    rb, cp, n_blocks, rp, row_spec, vec_spec, _ = _epi_specs(r, c)
    dx_out = jax.ShapeDtypeStruct((rp, cp), _np.dtype(dtype))
    return pl.pallas_call(
        functools.partial(_epi_bwd_dx_kernel, relu=relu, has_res=has_res),
        grid=(n_blocks,),
        in_specs=[row_spec] * (3 if relu else 2) + [vec_spec] * 5,
        out_shape=(dx_out, dx_out) if has_res else dx_out,
        out_specs=(row_spec, row_spec) if has_res else row_spec,
        interpret=interpret,
    )


def _epi_pad_rows(a, r, c):
    import jax.numpy as jnp

    rb, cp, n_blocks, rp = _epi_geom(r, c)
    return jnp.pad(a, ((0, rp - r), (0, cp - c)))


def _epi_forward(x2d, gamma, beta, res2d, eps, fix_gamma, relu, interpret):
    import jax.numpy as jnp
    from jax import lax

    r, c = x2d.shape
    has_res = res2d is not None
    dtype = str(x2d.dtype)
    rb, cp, _, _ = _epi_geom(r, c)
    stats_call = _epi_stats_compiled((r, c, dtype, interpret))
    apply_call = _epi_apply_compiled((r, c, dtype, relu, has_res, interpret))
    xp = _epi_pad_rows(x2d, r, c)
    # proxy: per-channel mean of the first row block (O(rb/R) read) — the
    # cancellation guard _bn_stats uses, not part of the exact result
    proxy = jnp.mean(x2d[:min(rb, r)].astype(jnp.float32), axis=0)
    s1, s2 = stats_call(xp, _vec8(proxy, cp))
    s1 = s1[0, :c] / r
    s2 = s2[0, :c] / r
    mean = proxy + s1
    var = jnp.maximum(s2 - jnp.square(s1), 0.0)
    inv = lax.rsqrt(var + eps)
    g1 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    scale = g1 * inv
    shift = beta.astype(jnp.float32) - mean * scale
    args = (xp, _epi_pad_rows(res2d, r, c)) if has_res else (xp,)
    out = apply_call(*args, _vec8(scale, cp), _vec8(shift, cp))[:r, :c]
    return out, mean, var, inv

def _epi_bwd_impl(eps, fix_gamma, relu, interpret, saved, cts, has_res):
    """Shared Pallas backward for both custom_vjp arities below."""
    import jax.numpy as jnp

    x2d, gamma, beta, mean, inv, out = saved
    r, c = x2d.shape
    ct = cts[0]                       # mean/var cotangents ignored
    dtype = str(x2d.dtype)
    _, cp, _, _ = _epi_geom(r, c)
    reduce_call = _epi_reduce_compiled((r, c, dtype, relu, interpret))
    dx_call = _epi_dx_compiled((r, c, dtype, relu, has_res, interpret))
    ctp = _epi_pad_rows(ct.astype(x2d.dtype), r, c)
    xp = _epi_pad_rows(x2d, r, c)
    rows = (ctp, xp, _epi_pad_rows(out, r, c)) if relu else (ctp, xp)
    meanv, invv = _vec8(mean, cp), _vec8(inv, cp)
    db, dg = reduce_call(*rows, meanv, invv)
    db = db[0, :c]
    dg = dg[0, :c]
    g1 = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    outs = dx_call(*rows, meanv, invv,
                   _vec8(g1 * inv, cp), _vec8(db / r, cp),
                   _vec8(dg / r, cp))
    if has_res:
        dx, dres = outs
        dres = dres[:r, :c]
    else:
        dx, dres = outs, None
    dx = dx[:r, :c]
    dgamma = (jnp.zeros_like(gamma) if fix_gamma
              else dg.astype(gamma.dtype))
    dbeta = db.astype(beta.dtype)
    return dx, dgamma, dbeta, dres


def _epi_save(x2d, gamma, beta, mean, inv, out, relu):
    # `out` is needed only for the ReLU mask; without relu the backward
    # neither saves nor streams it (it would be two wasted activation
    # reads per BN backward on the plain-BatchNorm path)
    return (x2d, gamma, beta, mean, inv, out if relu else None)


# module-level custom_vjp pair (one per arity), static config via
# nondiff_argnums — built lazily so importing this module never imports jax


@functools.lru_cache(maxsize=1)
def _epi_vjp_fns():
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
    def epi3(x2d, gamma, beta, eps, fix_gamma, relu, interpret):
        out, mean, var, _ = _epi_forward(
            x2d, gamma, beta, None, eps, fix_gamma, relu, interpret)
        return out, mean, var

    def epi3_fwd(x2d, gamma, beta, eps, fix_gamma, relu, interpret):
        out, mean, var, inv = _epi_forward(
            x2d, gamma, beta, None, eps, fix_gamma, relu, interpret)
        return (out, mean, var), _epi_save(x2d, gamma, beta, mean, inv,
                                           out, relu)

    def epi3_bwd(eps, fix_gamma, relu, interpret, saved, cts):
        dx, dgamma, dbeta, _ = _epi_bwd_impl(eps, fix_gamma, relu,
                                             interpret, saved, cts, False)
        return dx, dgamma, dbeta

    epi3.defvjp(epi3_fwd, epi3_bwd)

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
    def epi4(x2d, gamma, beta, res2d, eps, fix_gamma, relu, interpret):
        out, mean, var, _ = _epi_forward(
            x2d, gamma, beta, res2d, eps, fix_gamma, relu, interpret)
        return out, mean, var

    def epi4_fwd(x2d, gamma, beta, res2d, eps, fix_gamma, relu, interpret):
        out, mean, var, inv = _epi_forward(
            x2d, gamma, beta, res2d, eps, fix_gamma, relu, interpret)
        return (out, mean, var), _epi_save(x2d, gamma, beta, mean, inv,
                                           out, relu)

    def epi4_bwd(eps, fix_gamma, relu, interpret, saved, cts):
        return _epi_bwd_impl(eps, fix_gamma, relu, interpret, saved, cts,
                             True)

    epi4.defvjp(epi4_fwd, epi4_bwd)
    return epi3, epi4


def conv_epilogue(x, gamma, beta, residual=None, eps=1e-3, fix_gamma=False,
                  relu=True):
    """Fused BN(train-stats) + normalize + ReLU (+ residual add) over a
    channels-last activation x (..., C).

    Returns (out, batch_mean, batch_var); mean/var are f32 (C,) for the
    moving-stat update. Differentiable (custom_vjp, Pallas backward) w.r.t.
    x, gamma, beta and residual; the mean/var outputs' cotangents are
    ignored (same documented divergence as ops/nn.py _bn_train — they feed
    the never-differentiated moving-stat buffers). The custom_vjp pair is
    module-level (static config via nondiff args), so repeated calls trace
    the same function objects and jax's caches apply."""
    shape = x.shape
    c = shape[-1]
    x2d = x.reshape((-1, c))
    eps = float(eps)
    relu = bool(relu)
    fix_gamma = bool(fix_gamma)
    interpret = _use_interpret()
    epi3, epi4 = _epi_vjp_fns()
    if residual is None:
        out, mean, var = epi3(x2d, gamma, beta, eps, fix_gamma, relu,
                              interpret)
    else:
        out, mean, var = epi4(x2d, gamma, beta, residual.reshape((-1, c)),
                              eps, fix_gamma, relu, interpret)
    return out.reshape(shape), mean, var


# ---------------------------------------------------------------------------
# Paged decode attention (flash-decode): one query token per sequence
# against a block-allocated paged KV cache (serving/generate.py).
#
# Autoregressive decode is the q_len=1 degenerate case of attention, and
# its memory layout is dictated by the KV-cache allocator: each sequence's
# keys/values live scattered across fixed-size pages named by a per-
# sequence page table, not in one contiguous (L, D) slab. A dense gather
# (k_pages[page_tables] -> (B, max_pages, ...)) materializes a batch-wide
# padded COPY of every sequence's history in HBM per step; the Pallas
# kernel instead streams one PAGE per grid step straight from the paged
# array — the page table rides scalar-prefetch (SMEM), so the BlockSpec
# index_map picks each sequence's next page and nothing is ever copied
# out of the pool. Online softmax carries (m, l, acc) in VMEM scratch
# across the page axis, exactly the flash_attention recurrence with
# page-sized k-blocks. Known bound: the grid is static (B, max_pages), so
# a short sequence still DMAs its table's padding pages (masked to zero
# contribution) — per-sequence early exit needs dynamic grid bounds;
# until then the streamed bytes scale with max_pages, not actual length.
#
# Gate: MXTPU_PALLAS_DECODE — `auto` = kernel on TPU, jnp gather fallback
# elsewhere; `1` forces the kernel everywhere (interpret mode on CPU —
# the parity tests); `0` forces the jnp path.
# ---------------------------------------------------------------------------


def paged_attention_reference(q, k_pages, v_pages, page_tables, lengths,
                              sm_scale):
    """Dense-gather oracle (and CPU fallback): q (B, H, D); k_pages /
    v_pages (P, H, page_size, D); page_tables (B, max_pages) int32;
    lengths (B,) int32 — tokens [0, lengths[b]) of sequence b are live,
    laid out page_tables[b, t // page_size] slot t % page_size. A row
    with length 0 returns zeros-ish garbage that callers mask out (its
    scores are uniformly _NEG_INF, which is finite by design — no NaNs)."""
    import jax.numpy as jnp

    b, h, d = q.shape
    ps = k_pages.shape[2]
    maxp = page_tables.shape[1]
    k = k_pages[page_tables]            # (B, maxp, H, ps, D)
    v = v_pages[page_tables]
    k = jnp.moveaxis(k, 2, 1).reshape(b, h, maxp * ps, d)
    v = jnp.moveaxis(v, 2, 1).reshape(b, h, maxp * ps, d)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    ids = jnp.arange(maxp * ps)[None, None, :]
    s = jnp.where(ids < lengths[:, None, None], s, _NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bhl,bhld->bhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, sm_scale, ps, n_pages):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32) * sm_scale          # (Hp, Dp)
    k = k_ref[0].astype(jnp.float32)                     # (Hp, ps, Dp)
    v = v_ref[0].astype(jnp.float32)
    # per-head scores against this page: batch dim = head, contract = D
    s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)  # (Hp, ps)
    col = j * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < len_ref[b], s, _NEG_INF)
    m = m_scr[:, 0:1]
    l = l_scr[:, 0:1]
    new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc_scr[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)              # (Hp, Dp)
    m_scr[...] = jnp.broadcast_to(new_m, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l, l_scr.shape)
    acc_scr[...] = acc

    @pl.when(j == n_pages - 1)
    def _():
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l_scr[:, 0:1], 1e-30)).astype(o_ref.dtype)


@functools.lru_cache(maxsize=128)
def _paged_compiled(key):
    (b, h, d, n_pages, maxp, ps, dtype, sm_scale, interpret) = key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    hp = -(-h // 8) * 8
    dp = -(-d // 128) * 128

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # page_tables, lengths (SMEM)
        grid=(b, maxp),
        in_specs=[
            pl.BlockSpec((1, hp, dp), lambda bb, j, tbl, lens: (bb, 0, 0),
                         memory_space=pltpu.VMEM),                   # q
            # the paged gather: the page table names which KV page this
            # grid step streams into VMEM
            pl.BlockSpec((1, hp, ps, dp),
                         lambda bb, j, tbl, lens: (tbl[bb, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),                   # k
            pl.BlockSpec((1, hp, ps, dp),
                         lambda bb, j, tbl, lens: (tbl[bb, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),                   # v
        ],
        out_specs=pl.BlockSpec((1, hp, dp),
                               lambda bb, j, tbl, lens: (bb, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((hp, 128), jnp.float32),   # m
                        pltpu.VMEM((hp, 128), jnp.float32),   # l
                        pltpu.VMEM((hp, dp), jnp.float32)],   # acc
    )
    call = pl.pallas_call(
        functools.partial(_paged_kernel, sm_scale=sm_scale, ps=ps,
                          n_pages=maxp),
        out_shape=jax.ShapeDtypeStruct((b, hp, dp), _np.dtype(dtype)),
        grid_spec=grid_spec,
        interpret=interpret,
    )

    def run(q, k_pages, v_pages, page_tables, lengths):
        if hp == h and dp == d:
            # aligned geometry (the production case: H >= 8, Dh a lane
            # multiple): the page pool feeds the kernel directly and the
            # only HBM traffic is the pages actually attended
            return call(page_tables.astype(jnp.int32),
                        lengths.astype(jnp.int32), q, k_pages, v_pages)
        # unaligned geometry pays a padded COPY of the page pool per
        # call — acceptable for tiny test models, wrong for production:
        # pick H/Dh on the (8, 128) tile grid so this branch never runs
        qp = jnp.pad(q, ((0, 0), (0, hp - h), (0, dp - d)))
        kp = jnp.pad(k_pages, ((0, 0), (0, hp - h), (0, 0), (0, dp - d)))
        vp = jnp.pad(v_pages, ((0, 0), (0, hp - h), (0, 0), (0, dp - d)))
        out = call(page_tables.astype(jnp.int32),
                   lengths.astype(jnp.int32), qp, kp, vp)
        return out[:, :h, :d]

    return run


def paged_attention(q, k_pages, v_pages, page_tables, lengths,
                    sm_scale=None):
    """Flash-decode attention: one query token per sequence against a
    paged KV cache (docs/serving.md §Generation).

    q: (B, H, D) — the current token's per-head queries. k_pages /
    v_pages: (P, H, page_size, D) block-allocated cache. page_tables:
    (B, max_pages) int32 — sequence b's token t lives in page
    ``page_tables[b, t // page_size]`` slot ``t % page_size``; entries
    past the sequence's used pages must still be VALID page indices
    (they are masked by ``lengths``, never dereferenced out of bounds).
    lengths: (B,) int32 live-token counts (0 disables a padding row).
    """
    from .. import env as _env

    if sm_scale is None:
        sm_scale = 1.0 / float(_np.sqrt(q.shape[-1]))
    sm_scale = float(sm_scale)
    gate = (_env.raw("MXTPU_PALLAS_DECODE") or "auto").strip().lower()
    interpret = _use_interpret()
    if gate == "0" or (gate == "auto" and interpret):
        return paged_attention_reference(q, k_pages, v_pages, page_tables,
                                         lengths, sm_scale)
    b, h, d = q.shape
    n_pages, _, ps, _ = k_pages.shape
    maxp = page_tables.shape[1]
    run = _paged_compiled((b, h, d, n_pages, maxp, ps, str(q.dtype),
                           sm_scale, interpret))
    return run(q, k_pages, v_pages, page_tables, lengths)
