"""Pallas TPU kernels.

The reference ships hand-written CUDA where library kernels fall short
(src/operator/contrib/transformer.cu, fused RNN rnn-inl.h); the TPU-native
equivalent is Pallas. This module holds the kernels where XLA fusion alone
is insufficient — flash attention first: XLA materializes the (Lq, Lk)
score matrix in HBM, while the flash kernel streams K/V blocks through VMEM
with an online softmax, keeping the working set on-chip (HBM traffic
O(L·D) instead of O(L²)).

On non-TPU backends the same kernels run in interpret mode, so tests and
CPU development use one code path (the strategy SURVEY §4 prescribes for
cross-backend consistency).

Backward: recompute-based — the vjp of a plain jnp reference attention
(jax.checkpoint-style rematerialization). A Pallas backward kernel is the
round-2 upgrade; forward is where inference/serving time goes.
"""
from __future__ import annotations

import functools

import numpy as _np

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _use_interpret():
    import jax

    return jax.default_backend() != "tpu"


def _attention_reference(q, k, v, causal, sm_scale):
    """Plain jnp attention (the vjp source for backward; also the numerics
    oracle in tests)."""
    import jax.numpy as jnp

    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        row = jnp.arange(lq)[:, None]
        col = jnp.arange(lk)[None, :]
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, lq, lk,
                block_q, block_k, n_kblocks):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (block_q, D)
    d = q.shape[-1]

    row_ids = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        col_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col_ids < lk
        if causal:
            mask = jnp.logical_and(mask, col_ids <= row_ids)
        s = jnp.where(mask, s, _NEG_INF)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, l, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    # causal: blocks strictly above the diagonal contribute nothing — still
    # iterated (masked) to keep the grid static; XLA pipelines the DMA anyway
    m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.lru_cache(maxsize=256)
def _fwd_compiled(shape_key):
    (bh, lq, lk, d, dtype, causal, sm_scale, interpret) = shape_key
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q = min(128, lq)
    block_k = min(128, lk)
    n_q = -(-lq // block_q)
    n_k = -(-lk // block_k)
    lq_pad, lk_pad = n_q * block_q, n_k * block_k

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               lq=lq, lk=lk, block_q=block_q, block_k=block_k,
                               n_kblocks=n_k)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, lq_pad, d), _np.dtype(dtype)),
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lk_pad, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )

    def run(q, k, v):
        qp = jnp.pad(q, ((0, 0), (0, lq_pad - lq), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, lk_pad - lk), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, lk_pad - lk), (0, 0)))
        return call(qp, kp, vp)[:, :lq, :]

    return run


def _flash_fwd(q, k, v, causal, sm_scale):
    bh, lq, d = q.shape
    lk = k.shape[1]
    run = _fwd_compiled((bh, lq, lk, d, str(q.dtype), bool(causal),
                         float(sm_scale), _use_interpret()))
    return run(q, k, v)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Flash attention over (..., L, D) tensors (leading dims are batched).

    TPU-native replacement for attention assembled from the reference's
    primitive ops (batch_dot + softmax + batch_dot, e.g.
    src/operator/contrib/transformer.cc usage); same math, O(L·D) HBM
    traffic. Differentiable via recompute-vjp.
    """
    import jax
    import jax.numpy as jnp

    if sm_scale is None:
        sm_scale = 1.0 / float(_np.sqrt(q.shape[-1]))
    sm_scale = float(sm_scale)

    lead = q.shape[:-2]
    lq, d = q.shape[-2:]
    lk = k.shape[-2]
    qf = q.reshape((-1, lq, d))
    kf = k.reshape((-1, lk, d))
    vf = v.reshape((-1, lk, d))

    @jax.custom_vjp
    def attn(qf, kf, vf):
        return _flash_fwd(qf, kf, vf, causal, sm_scale)

    def fwd(qf, kf, vf):
        return attn(qf, kf, vf), (qf, kf, vf)

    def bwd(res, g):
        qf, kf, vf = res
        _, pull = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale),
            qf, kf, vf)
        return pull(g)

    attn.defvjp(fwd, bwd)
    return attn(qf, kf, vf).reshape(lead + (lq, d))
