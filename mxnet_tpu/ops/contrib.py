"""Contrib ops (reference: src/operator/contrib/**).

Subset covering the reference's model configs: transformer helpers
(transformer.cc:34 div_sqrt_dim), detection ops for SSD (multibox_prior/
target/detection multibox_*.cc, box_nms bounding_box.cc), roi_align
(roi_align.cc), resize ops (bilinear_resize-inl.h, adaptive_avg_pooling.cc),
fft (fft-inl.h), the `quadratic` tutorial op (quadratic_op-inl.h), boolean
mask and index ops. Dynamic-output-shape ops (box_nms, boolean_mask) keep
static shapes by returning masked/padded results with -1 sentinels, the
standard TPU formulation (SURVEY §7.8(b))."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import register


@register("_contrib_div_sqrt_dim", aliases=("div_sqrt_dim",))
def div_sqrt_dim(data):
    """reference: src/operator/contrib/transformer.cc:34 — scale by 1/sqrt(d)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """reference: src/operator/contrib/quadratic_op-inl.h (the tutorial op)."""
    return a * jnp.square(data) + b * data + c


@register("_contrib_arange_like", aliases=("arange_like",))
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = data.size
        out = start + step * jnp.arange(n, dtype=data.dtype)
        return out.reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D", "bilinear_resize_2d"))
def bilinear_resize_2d(data, height=1, width=1, scale_height=None, scale_width=None,
                       mode="size", align_corners=True):
    """reference: bilinear_resize-inl.h — the default resize maps corners
    to corners (align_corners=True, src = dst*(in-1)/(out-1)); with
    align_corners=False it is the half-pixel convention, which is what
    jax.image.resize implements."""
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(h * scale_height)
        width = int(w * (scale_width if scale_width is not None
                         else scale_height))
    if not align_corners:
        return jax.image.resize(data, (n, c, height, width), method="bilinear")

    def axis_coords(in_sz, out_sz):
        if out_sz == 1:
            return jnp.zeros((1,))
        return jnp.linspace(0.0, in_sz - 1.0, out_sz)

    ys = axis_coords(h, height)
    xs = axis_coords(w, width)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    wy = (ys - y0).astype(data.dtype).reshape((1, 1, height, 1))
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wx = (xs - x0).astype(data.dtype).reshape((1, 1, 1, width))
    rows0 = jnp.take(data, y0, axis=2)
    rows1 = jnp.take(data, y1, axis=2)
    rowi = rows0 * (1 - wy) + rows1 * wy          # (n, c, height, w)
    c0 = jnp.take(rowi, x0, axis=3)
    c1 = jnp.take(rowi, x1, axis=3)
    return c0 * (1 - wx) + c1 * wx


@register("_contrib_AdaptiveAvgPooling2D", aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, output_size=()):
    n, c, h, w = data.shape
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size if len(output_size) == 2 else (output_size[0],) * 2
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


@register("_contrib_boolean_mask", aliases=("boolean_mask",), num_outputs=1)
def boolean_mask(data, index, axis=0):
    """Static-shape variant: invalid rows are zeroed and compacted to the
    front; the true count is data-dependent so TPU keeps the full size
    (reference returns a dynamically-sized array, contrib/boolean_mask.cc)."""
    mask = index.astype(bool)
    order = jnp.argsort(~mask, stable=True)
    gathered = jnp.take(data, order, axis=axis)
    keep = jnp.sort(mask)[::-1]
    bshape = (-1,) + (1,) * (data.ndim - 1 - axis)
    return gathered * keep.reshape(bshape).astype(data.dtype)


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new):
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_index_array", aliases=("index_array",))
def index_array(data, axes=None):
    """Index coordinates of every element: shape data.shape + (len(axes),)
    (reference: src/operator/contrib/index_array.cc — the full data shape is
    kept even when only a subset of axes is requested)."""
    axes = tuple(axes) if axes else tuple(range(data.ndim))
    comps = []
    for a in axes:
        shape1 = [1] * data.ndim
        shape1[a] = data.shape[a]
        comps.append(jnp.broadcast_to(
            jnp.arange(data.shape[a]).reshape(shape1), data.shape))
    # int32 (int64 policy): avoids the per-call x64 truncation warning
    return jnp.stack(comps, axis=-1).astype(jnp.int32)


@register("_contrib_fft", aliases=("fft",))
def fft(data, compute_size=128):
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    return jnp.stack([out.real, out.imag], axis=-1).reshape(data.shape[:-1] + (2 * data.shape[-1],))


@register("_contrib_ifft", aliases=("ifft",))
def ifft(data, compute_size=128):
    n = data.shape[-1] // 2
    cplx = data.reshape(data.shape[:-1] + (n, 2))
    out = jnp.fft.ifft(cplx[..., 0] + 1j * cplx[..., 1], axis=-1)
    return out.real.astype(jnp.float32) * n


# --------------------------------------------------------------------------
# ROI ops (reference: roi_align.cc, ../roi_pooling.cc)
# --------------------------------------------------------------------------

def _bilinear_sample(feat, y, x):
    """feat: (C,H,W); y,x scalars (traced)."""
    h, w = feat.shape[1], feat.shape[2]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0

    def g(yy, xx):
        yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        return feat[:, yi, xi]

    return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x0 + 1) * (1 - wy) * wx
            + g(y0 + 1, x0) * wy * (1 - wx) + g(y0 + 1, x0 + 1) * wy * wx)


@register("_contrib_ROIAlign", aliases=("ROIAlign", "roi_align"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=2,
              position_sensitive=False, aligned=False):
    ph, pw = pooled_size
    sr = max(int(sample_ratio), 1)
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - offset, roi[2] * spatial_scale - offset, \
            roi[3] * spatial_scale - offset, roi[4] * spatial_scale - offset
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bh, bw = rh / ph, rw / pw
        feat = data[jnp.clip(bidx, 0, data.shape[0] - 1)]

        iy = (jnp.arange(ph)[:, None, None, None] * bh + y1
              + (jnp.arange(sr)[None, None, :, None] + 0.5) * bh / sr)
        ix = (jnp.arange(pw)[None, :, None, None] * bw + x1
              + (jnp.arange(sr)[None, None, None, :] + 0.5) * bw / sr)
        ys = jnp.broadcast_to(iy, (ph, pw, sr, sr)).reshape(-1)
        xs = jnp.broadcast_to(ix, (ph, pw, sr, sr)).reshape(-1)
        samples = jax.vmap(lambda y, x: _bilinear_sample(feat, y, x))(ys, xs)
        samples = samples.reshape(ph, pw, sr * sr, -1).mean(axis=2)
        return jnp.moveaxis(samples, -1, 0)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    ph, pw = pooled_size

    def one_roi(roi):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, data.shape[0] - 1)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        feat = data[bidx]
        h, w = feat.shape[1], feat.shape[2]
        gy = jnp.arange(h, dtype=jnp.float32)
        gx = jnp.arange(w, dtype=jnp.float32)
        biny = jnp.clip(jnp.floor((gy - y1) * ph / rh), -1, ph - 1)
        binx = jnp.clip(jnp.floor((gx - x1) * pw / rw), -1, pw - 1)
        inside_y = (gy >= y1) & (gy <= y2)
        inside_x = (gx >= x1) & (gx <= x2)
        out = jnp.full((feat.shape[0], ph, pw), -jnp.inf, feat.dtype)
        oh = jnp.where(inside_y, biny, ph).astype(jnp.int32)
        ow = jnp.where(inside_x, binx, pw).astype(jnp.int32)
        padded = jnp.full((feat.shape[0], ph + 1, pw + 1), -jnp.inf, feat.dtype)
        padded = padded.at[:, oh[:, None], ow[None, :]].max(feat)
        out = padded[:, :ph, :pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


# --------------------------------------------------------------------------
# SSD / detection ops (reference: multibox_prior.cc, multibox_target.cc,
# multibox_detection.cc, bounding_box.cc)
# --------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                   offsets=(0.5, 0.5)):
    import numpy as np

    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (np.arange(h) + offsets[0]) * step_y
    cx = (np.arange(w) + offsets[1]) * step_x
    cy, cx = np.meshgrid(cy, cx, indexing="ij")
    boxes = []
    num = len(sizes) + len(ratios) - 1
    for i in range(num):
        if i < len(sizes):
            s = sizes[i]
            bw = bh = s / 2.0
            bw *= np.sqrt(ratios[0])
            bh /= np.sqrt(ratios[0])
        else:
            r = ratios[i - len(sizes) + 1]
            bw = sizes[0] / 2.0 * np.sqrt(r)
            bh = sizes[0] / 2.0 / np.sqrt(r)
        boxes.append(np.stack([cx - bw, cy - bh, cx + bw, cy + bh], axis=-1))
    out = np.stack(boxes, axis=2).reshape(1, -1, 4).astype(np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return jnp.asarray(out)


def _box_iou_corner(a, b):
    """a: (..., 4), b: (..., 4) corner format; broadcast IoU."""
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1]), 0.0)
    area_b = jnp.maximum((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]), 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):
    return _box_iou_corner(lhs[..., :, None, :], rhs[..., None, :, :])


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",), num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor matching + target encoding for SSD training
    (reference: src/operator/contrib/multibox_target.cc)."""
    anchors = anchor.reshape(-1, 4)  # (A,4)
    A = anchors.shape[0]

    def per_sample(lab, cls_p):
        # lab: (M, 5+) [cls, x1, y1, x2, y2]; cls_p: (C, A) raw predictions
        valid = lab[:, 0] >= 0
        ious = _box_iou_corner(anchors[:, None, :], lab[None, :, 1:5])  # (A,M)
        ious = jnp.where(valid[None, :], ious, 0.0)
        best_gt = jnp.argmax(ious, axis=1)
        best_iou = jnp.max(ious, axis=1)
        matched = best_iou > overlap_threshold
        # force-match the best anchor for each gt
        best_anchor = jnp.argmax(ious, axis=0)  # (M,)
        forced = jnp.zeros(A, bool).at[best_anchor].set(valid)
        matched = matched | forced
        gt = lab[best_gt]
        cls_target = jnp.where(matched, gt[:, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining (reference multibox_target.cc): unmatched
            # anchors below the mining IoU threshold are ranked by their
            # predicted non-background confidence; the hardest ratio*num_pos
            # stay background, the rest get ignore_label. Static-shape: the
            # dynamic quota is a rank comparison, not a gather.
            prob = jax.nn.softmax(cls_p, axis=0)           # (C, A)
            hardness = 1.0 - prob[0]                        # non-bg confidence
            candidate = (~matched) & (best_iou < negative_mining_thresh)
            score = jnp.where(candidate, hardness, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.zeros(A, jnp.int32).at[order].set(jnp.arange(A, dtype=jnp.int32))
            quota = jnp.maximum(
                (negative_mining_ratio * jnp.sum(matched)).astype(jnp.int32),
                jnp.int32(minimum_negative_samples))
            keep_neg = candidate & (rank < quota)
            cls_target = jnp.where(matched, cls_target,
                                   jnp.where(keep_neg, 0.0, float(ignore_label)))
        # encode regression targets (center form, variances)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
        gh = jnp.maximum(gt[:, 4] - gt[:, 2], 1e-12)
        gcx = (gt[:, 1] + gt[:, 3]) / 2
        gcy = (gt[:, 2] + gt[:, 4]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-12) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-12) / variances[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-12)) / variances[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-12)) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0)
        loc_mask = jnp.where(matched[:, None], 1.0, 0.0)
        loc_mask = jnp.broadcast_to(loc_mask, (A, 4))
        return loc_t.reshape(-1), loc_mask.reshape(-1), cls_target

    # targets are training labels, not differentiable functions of the
    # predictions (reference MultiBoxTarget registers no gradient)
    loc_target, loc_mask, cls_target = jax.vmap(per_sample)(
        lax.stop_gradient(label), lax.stop_gradient(cls_pred))
    return loc_target, loc_mask, cls_target


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + per-class NMS, static shapes (invalid -> id=-1).
    reference: src/operator/contrib/multibox_detection.cc"""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def per_sample(cls_p, loc_p):
        # cls_p: (C, A); loc_p: (A*4,)
        loc = loc_p.reshape(A, 4)
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw / 2
        h = jnp.exp(loc[:, 3] * variances[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = cls_p[1:] if background_id == 0 else cls_p  # (C-1, A)
        cls_id = jnp.argmax(scores, axis=0).astype(jnp.float32)
        score = jnp.max(scores, axis=0)
        keep_score = score > threshold
        # greedy NMS over all anchors (class-aware unless force_suppress)
        order = jnp.argsort(-score)
        boxes_o = boxes[order]
        ids_o = cls_id[order]
        score_o = score[order]
        keep_o = keep_score[order]
        ious = _box_iou_corner(boxes_o[:, None, :], boxes_o[None, :, :])
        same = jnp.ones((A, A), bool) if force_suppress else (ids_o[:, None] == ids_o[None, :])
        sup_mat = (ious > nms_threshold) & same

        def body(i, alive):
            cur = alive[i]
            kill = sup_mat[i] & (jnp.arange(A) > i) & cur
            return alive & ~kill

        alive = lax.fori_loop(0, A, body, keep_o)
        out_id = jnp.where(alive & keep_o, ids_o, -1.0)
        return jnp.concatenate([out_id[:, None], score_o[:, None], boxes_o], axis=-1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Static-shape NMS: suppressed entries get score column set to -1
    (reference: src/operator/contrib/bounding_box.cc)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])

    def per_batch(d):
        n = d.shape[0]
        score = d[:, score_index]
        boxes = lax.dynamic_slice_in_dim(d, coord_start, 4, axis=1)
        if in_format == "center":
            cx, cy, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
            boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        valid = score > valid_thresh
        order = jnp.argsort(-score)
        d_o = d[order]
        b_o = boxes[order]
        v_o = valid[order]
        if id_index >= 0 and not force_suppress:
            ids = d_o[:, id_index]
            same = ids[:, None] == ids[None, :]
        else:
            same = jnp.ones((n, n), bool)
        ious = _box_iou_corner(b_o[:, None, :], b_o[None, :, :])
        sup = (ious > overlap_thresh) & same

        def body(i, alive):
            cur = alive[i]
            kill = sup[i] & (jnp.arange(n) > i) & cur
            return alive & ~kill

        alive = lax.fori_loop(0, n, body, v_o)
        out = d_o.at[:, score_index].set(jnp.where(alive, d_o[:, score_index], -1.0))
        return out

    out = jax.vmap(per_batch)(flat)
    return out.reshape(shape)


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(data * sign)


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0, use_linear=False):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        depth = d.shape[-1]
        onehot = jax.nn.one_hot(l.astype(jnp.int32), depth, dtype=d.dtype)
        score_gt = jnp.sum(d * onehot, axis=-1, keepdims=True)
        if use_linear:
            viol = ((margin - (score_gt - d)) > 0).astype(d.dtype) * (1 - onehot)
            grad = viol - onehot * jnp.sum(viol, axis=-1, keepdims=True)
        else:
            m = jnp.maximum(margin - (score_gt - d), 0.0) * (1 - onehot)
            grad = 2 * m - 2 * onehot * jnp.sum(m, axis=-1, keepdims=True)
        return grad * regularization_coefficient, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("_contrib_flash_attention", aliases=("flash_attention",))
def _contrib_flash_attention(q, k, v, causal=False, sm_scale=None):
    """Pallas flash attention over (..., L, D) inputs (ops/pallas_kernels.py;
    the TPU replacement for batch_dot+softmax+batch_dot attention assembled
    from reference primitives, src/operator/contrib/transformer.cc)."""
    from . import pallas_kernels

    return pallas_kernels.flash_attention(q, k, v, causal=causal,
                                          sm_scale=sm_scale)


# --------------------------------------------------------------------------
# RPN Proposal (reference: src/operator/contrib/proposal-inl.h:93 — anchors
# + bbox deltas -> clip -> min-size filter -> top-k -> NMS -> fixed-count
# rois). Static shapes throughout: top-k and the NMS alive-mask keep XLA
# happy; short outputs pad by repeating the best proposal like the
# reference's workspace fill.
# --------------------------------------------------------------------------

def _rpn_anchors(h, w, stride, scales, ratios):
    import numpy as np

    base = float(stride)
    anchors = []
    for r in ratios:
        for s in scales:
            size = base * base * s * s
            w_a = np.sqrt(size / r)
            h_a = w_a * r
            anchors.append([-(w_a - 1) / 2, -(h_a - 1) / 2,
                            (w_a - 1) / 2, (h_a - 1) / 2])
    base_a = np.asarray(anchors, np.float32)          # (A, 4)
    cy, cx = np.meshgrid(np.arange(h) * stride, np.arange(w) * stride,
                         indexing="ij")
    shift = np.stack([cx, cy, cx, cy], axis=-1).reshape(-1, 1, 4)
    return (shift + base_a[None]).reshape(-1, 4)      # (H*W*A, 4)


@register("_contrib_Proposal", num_outputs=-1,
          num_outputs_fn=lambda attrs: 2 if attrs.get("output_score") else 1,
          aliases=("Proposal", "_contrib_MultiProposal", "MultiProposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """cls_prob (B, 2A, H, W), bbox_pred (B, 4A, H, W), im_info (B, 3)
    -> rois (B*post_nms_top_n, 5) [batch_idx, x1, y1, x2, y2]."""
    b, c2a, h, w = cls_prob.shape
    na = c2a // 2
    anchors = jnp.asarray(_rpn_anchors(h, w, feature_stride, scales, ratios))
    total = anchors.shape[0]
    pre_n = min(int(rpn_pre_nms_top_n), total)
    post_n = int(rpn_post_nms_top_n)

    def per_image(scores, deltas, info):
        # scores (2A, H, W) -> fg (A, H, W) -> (H*W*A,)
        fg = scores[na:].transpose(1, 2, 0).reshape(-1)
        d = deltas.reshape(na, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        acx = anchors[:, 0] + 0.5 * (aw - 1)
        acy = anchors[:, 1] + 0.5 * (ah - 1)
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        pw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        ph = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        x1 = cx - 0.5 * (pw - 1)
        y1 = cy - 0.5 * (ph - 1)
        x2 = cx + 0.5 * (pw - 1)
        y2 = cy + 0.5 * (ph - 1)
        # clip to image (im_info = [height, width, scale])
        x1 = jnp.clip(x1, 0, info[1] - 1.0)
        y1 = jnp.clip(y1, 0, info[0] - 1.0)
        x2 = jnp.clip(x2, 0, info[1] - 1.0)
        y2 = jnp.clip(y2, 0, info[0] - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        min_size = rpn_min_size * info[2]
        keep = ((x2 - x1 + 1.0) >= min_size) & ((y2 - y1 + 1.0) >= min_size)
        score = jnp.where(keep, fg, -jnp.inf)
        top_s, top_i = jax.lax.top_k(score, pre_n)
        top_b = boxes[top_i]

        def body(i, alive):
            # one IoU row per step: keeps NMS memory O(pre_n) instead of a
            # pre_n^2 matrix (6000^2 f32 = 144MB/image at the default top_n)
            row = _box_iou_corner(top_b[i][None, :], top_b)
            cur = alive[i]
            kill = (row > threshold) & (jnp.arange(pre_n) > i) & cur
            return alive & ~kill

        alive = lax.fori_loop(0, pre_n, body,
                              jnp.isfinite(top_s))
        # order survivors first (stable), pad by repeating the best
        rank = jnp.argsort(~alive, stable=True)
        sel = rank[:post_n] if post_n <= pre_n else \
            jnp.concatenate([rank, jnp.zeros(post_n - pre_n, rank.dtype)])
        out_boxes = top_b[sel]
        out_alive = alive[sel]
        out_boxes = jnp.where(out_alive[:, None], out_boxes, top_b[0])
        out_score = jnp.where(out_alive, top_s[sel], top_s[0])
        return out_boxes, out_score

    boxes, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    batch_ids = jnp.repeat(jnp.arange(b, dtype=boxes.dtype), post_n)
    rois = jnp.concatenate([batch_ids[:, None],
                            boxes.reshape(-1, 4)], axis=-1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


# --------------------------------------------------------------------------
# DeformableConvolution (reference:
# src/operator/contrib/deformable_convolution-inl.h:99 — bilinear sampling
# at learned per-tap offsets, then a standard grouped conv contraction).
# TPU-native: the sampled column tensor is built with vectorized gathers
# (XLA fuses the 4-corner interpolation) and contracted with one einsum on
# the MXU — no explicit im2col buffer in HBM.
# --------------------------------------------------------------------------

@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(),
                           stride=(), dilate=(), pad=(), num_filter=0,
                           num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=0, layout=None):
    n, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    g = int(num_group)
    dg = int(num_deformable_group)
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    oy = jnp.arange(oh) * sh - ph
    ox = jnp.arange(ow) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    # base sampling grids (kh, kw, oh, ow)
    base_y = jnp.broadcast_to(
        (oy[None, None, :, None] + ky[:, None, None, None]).astype(data.dtype),
        (kh, kw, oh, ow))
    base_x = jnp.broadcast_to(
        (ox[None, None, None, :] + kx[None, :, None, None]).astype(data.dtype),
        (kh, kw, oh, ow))

    # reference offset layout: (N, dg*2*kh*kw, OH, OW), y before x per tap
    off = offset.reshape(n, dg, kh * kw, 2, oh, ow) \
                .reshape(n, dg, kh, kw, 2, oh, ow)
    sy = base_y[None, None] + off[:, :, :, :, 0]
    sx = base_x[None, None] + off[:, :, :, :, 1]   # (N, dg, kh, kw, oh, ow)

    def bilinear(img, y, x):
        # img (C', H, W); y/x (kh, kw, oh, ow)
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def at(yy, xx):
            inb = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = img[:, yc, xc]                      # (C', kh, kw, oh, ow)
            return jnp.where(inb[None], v, 0.0)

        return (at(y0, x0) * ((1 - wy) * (1 - wx))[None]
                + at(y0, x0 + 1) * ((1 - wy) * wx)[None]
                + at(y0 + 1, x0) * (wy * (1 - wx))[None]
                + at(y0 + 1, x0 + 1) * (wy * wx)[None])

    def per_sample(img, y, x):
        # img (C, H, W) split into dg channel groups sharing offsets
        imgs = img.reshape(dg, c // dg, h, w)
        cols = jax.vmap(bilinear)(imgs, y, x)       # (dg, C/dg, kh, kw, ...)
        return cols.reshape(c, kh, kw, oh, ow)

    cols = jax.vmap(per_sample)(data, sy, sx)       # (N, C, kh, kw, oh, ow)
    cols = cols.reshape(n, g, c // g, kh, kw, oh, ow)
    wgt = weight.reshape(g, num_filter // g, c // g, kh, kw)
    out = jnp.einsum("ngcijyx,gocij->ngoyx", cols, wgt,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, num_filter, oh, ow).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# --------------------------------------------------------------------------
# Switch-style mixture-of-experts FFN (NOT in the reference — the expert-
# parallel extension SURVEY §2.3 lists as a TPU-native goal). Top-1 routing
# with capacity, dense dispatch/combine einsums (the GSPMD formulation:
# under a mesh with an `ep` axis the expert tables shard over `ep` and XLA
# lowers the token->expert resharding to an all_to_all over ICI).
# --------------------------------------------------------------------------

@register("_contrib_switch_moe", num_outputs=2, num_visible_outputs=2,
          aliases=("switch_moe",))
def switch_moe(data, gate_weight, expert_w_in, expert_w_out,
               capacity_factor=1.25):
    """data (..., d); gate_weight (E, d); expert tables (E, d, h)/(E, h, d).
    Returns (output (..., d), aux_loss ()) — aux is the Switch load-balance
    loss E * sum_e(frac_tokens_e * frac_probs_e). Exactly `topk_moe` at
    k=1 with unnormalized gates (one shared dispatch body; the router
    z-loss output is dropped — XLA dead-code-eliminates it under jit)."""
    out, lb, _z = topk_moe(data, gate_weight, expert_w_in, expert_w_out,
                           k=1, capacity_factor=capacity_factor,
                           normalize_gates=False)
    return out, lb


@register("_contrib_topk_moe", num_outputs=3, num_visible_outputs=3,
          aliases=("topk_moe",))
def topk_moe(data, gate_weight, expert_w_in, expert_w_out, k=2,
             capacity_factor=1.25, normalize_gates=True):
    """Top-k MoE routing (GShard/Mixtral-style generalization of
    `switch_moe`; k=1 reproduces Switch). data (..., d); gate_weight (E, d);
    expert tables (E, d, h)/(E, h, d). Returns

      (output (..., d), lb_loss (), z_loss ())

    - lb_loss: load-balance loss E * sum_e(frac_tokens_e * frac_probs_e),
      with frac_tokens counting all k assignments (each token contributes
      1/k per choice so a balanced router scores 1.0, as at k=1).
    - z_loss: router z-loss mean_t(logsumexp(logits_t)^2) (ST-MoE) — keeps
      router logits small; scale with your own coefficient (~1e-3).

    Capacity is `capacity_factor * k * T / E` slots per expert, shared
    across choices in priority order (choice 0 claims slots before choice 1,
    matching the GShard dispatch priority); overflow tokens drop that
    choice. The dispatch/combine einsums are the GSPMD formulation: with an
    `ep` mesh axis the (E, C, d) activations shard over `ep` and XLA lowers
    the resharding to ICI all_to_alls, exactly as in `switch_moe`."""
    k = int(k)
    if k < 1:
        raise ValueError("topk_moe: k must be >= 1")
    lead = data.shape[:-1]
    d = data.shape[-1]
    tokens = data.reshape(-1, d)
    t = tokens.shape[0]
    e = gate_weight.shape[0]
    if k > e:
        raise ValueError("topk_moe: k=%d > num_experts=%d" % (k, e))
    cap = max(1, int(capacity_factor * k * t / e))

    logits = jnp.einsum("td,ed->te", tokens, gate_weight,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, experts = jax.lax.top_k(probs, k)           # (T, k)
    if normalize_gates and k > 1:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9)

    # Per-choice dispatch with capacity shared across choices: choice j's
    # queue positions start after every earlier choice's claims (k is a
    # small static int, so this Python loop unrolls under trace).
    counts = jnp.zeros((e,), jnp.float32)
    combine = jnp.zeros((t, e, cap), jnp.float32)
    dispatch = jnp.zeros((t, e, cap), jnp.float32)
    onehot_sum = jnp.zeros((t, e), jnp.float32)
    for j in range(k):
        onehot = jax.nn.one_hot(experts[:, j], e, dtype=jnp.float32)
        onehot_sum = onehot_sum + onehot
        pos = ((jnp.cumsum(onehot, axis=0) - 1.0) + counts[None, :]) * onehot
        keep = (pos < cap) & (onehot > 0)
        slot = jax.nn.one_hot(pos.sum(axis=-1).astype(jnp.int32), cap,
                              dtype=jnp.float32)
        dispatch_j = keep.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        dispatch = dispatch + dispatch_j
        combine = combine + dispatch_j * gate_vals[:, j].astype(
            jnp.float32)[:, None, None]
        counts = counts + onehot.sum(axis=0)

    xe = jnp.einsum("tec,td->ecd", dispatch, tokens,
                    preferred_element_type=jnp.float32).astype(data.dtype)
    he = jax.nn.relu(jnp.einsum("ecd,edh->ech", xe, expert_w_in,
                                preferred_element_type=jnp.float32)
                     .astype(data.dtype))
    ye = jnp.einsum("ech,ehd->ecd", he, expert_w_out,
                    preferred_element_type=jnp.float32).astype(data.dtype)
    # combine stays float32 into the mixed-dtype contraction: gates keep
    # their full softmax precision even for bf16 activations
    out = jnp.einsum("tec,ecd->td", combine, ye,
                     preferred_element_type=jnp.float32).astype(data.dtype)

    frac_tokens = onehot_sum.mean(axis=0) / k
    frac_probs = probs.mean(axis=0)
    lb = (frac_tokens * frac_probs).sum() * e
    z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return (out.reshape(lead + (d,)), lb.astype(jnp.float32),
            z.astype(jnp.float32))
