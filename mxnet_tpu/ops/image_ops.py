"""Image op family (reference: src/operator/image/ — _image_to_tensor,
_image_normalize, _image_resize, _image_crop; exposed as the
`mx.nd.image.*` / `mx.sym.image.*` namespaces). HWC layout in,
reference semantics: to_tensor converts to CHW float [0,1]; normalize is
per-channel on CHW; resize/crop operate on HWC (batched NHWC allowed)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import register


def _per_channel(val, c, dtype):
    arr = jnp.asarray(val, dtype)
    if arr.ndim == 0:
        arr = jnp.broadcast_to(arr, (c,))
    return arr


@register("_image_to_tensor", aliases=("to_tensor",))
def image_to_tensor(data):
    """(H,W,C) or (N,H,W,C) uint8/float [0,255] -> (C,H,W)/(N,C,H,W)
    float32 [0,1] (reference: image_random.cc _image_to_tensor)."""
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize", aliases=("image_normalize",))
def image_normalize(data, mean=0.0, std=1.0):
    """(C,H,W)/(N,C,H,W) float: (x - mean[c]) / std[c] (reference:
    image_random.cc _image_normalize — type-checked to float there too;
    an integer input would silently truncate mean/std to 0)."""
    if not jnp.issubdtype(data.dtype, jnp.floating):
        from ..base import MXNetError

        raise MXNetError(
            "image.normalize expects a float input (run to_tensor first); "
            "got %s" % data.dtype)
    c = data.shape[0] if data.ndim == 3 else data.shape[1]
    m = _per_channel(mean, c, data.dtype)
    s = _per_channel(std, c, data.dtype)
    shape = (c, 1, 1) if data.ndim == 3 else (1, c, 1, 1)
    return (data - m.reshape(shape)) / s.reshape(shape)


@register("_image_resize", aliases=("image_resize",))
def image_resize(data, size=(), keep_ratio=False, interp=1):
    """(H,W,C)/(N,H,W,C) resize (reference: resize.cc). `size` is an int
    (short side when keep_ratio else square) or (w, h). interp 0=nearest,
    1=bilinear (OpenCV codes; others lower to bilinear on TPU)."""
    from ..base import MXNetError

    batched = data.ndim == 4
    h, w = (data.shape[1], data.shape[2]) if batched \
        else (data.shape[0], data.shape[1])
    if isinstance(size, (tuple, list)) and len(size) not in (1, 2):
        raise MXNetError("image.resize: size must be an int, (s,) or "
                         "(w, h); got %r" % (size,))
    if isinstance(size, (tuple, list)) and len(size) == 2:
        new_w, new_h = int(size[0]), int(size[1])
    else:
        s = int(size[0]) if isinstance(size, (tuple, list)) else int(size)
        if s < 1:
            raise MXNetError("image.resize: size is required and must be "
                             "positive; got %r" % (size,))
        if keep_ratio:
            # reference resize-inl.h truncates (static_cast<int>), not
            # rounds — ported pipelines hard-code these shapes
            if h < w:
                new_h, new_w = s, max(1, w * s // h)
            else:
                new_w, new_h = s, max(1, h * s // w)
        else:
            new_w = new_h = s
    method = "nearest" if int(interp) == 0 else "linear"
    if batched:
        out_shape = (data.shape[0], new_h, new_w, data.shape[3])
    else:
        out_shape = (new_h, new_w, data.shape[2])
    out = jax.image.resize(data.astype(jnp.float32), out_shape,
                           method=method)
    if jnp.issubdtype(data.dtype, jnp.integer):
        # round like OpenCV's saturate_cast (plain astype truncates,
        # biasing uint8 outputs ~0.5 LSB dark)
        info = jnp.iinfo(data.dtype)
        return jnp.clip(jnp.round(out), info.min, info.max) \
            .astype(data.dtype)
    return out


@register("_image_crop", aliases=("image_crop",))
def image_crop(data, x=0, y=0, width=1, height=1):
    """Fixed-window crop at (x, y) of size (width, height) on
    (H,W,C)/(N,H,W,C) (reference: crop.cc _image_crop)."""
    x, y, width, height = int(x), int(y), int(width), int(height)
    if data.ndim == 3:
        return jax.lax.slice(data, (y, x, 0),
                             (y + height, x + width, data.shape[2]))
    return jax.lax.slice(data, (0, y, x, 0),
                         (data.shape[0], y + height, x + width,
                          data.shape[3]))
