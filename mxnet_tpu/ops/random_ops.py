"""Random sampling ops.

Reference: src/operator/random/sample_op.cc (uniform/normal/gamma/exponential/
poisson/negative_binomial/randint), multisample_op.cc, shuffle_op.cc,
sample_multinomial_op.cc. TPU-native: every op consumes one threefry subkey
from the global chain (mxnet_tpu/random.py) — stateless, reproducible, and
traceable (the key is a runtime input under jit, SURVEY §7.8(e))."""
from __future__ import annotations

from . import register

import jax
import jax.numpy as jnp

from ..base import np_dtype, device_int_dtype as _device_int_dtype


@register("_random_uniform", needs_rng=True, aliases=("uniform", "random_uniform"))
def random_uniform(rng, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(rng, shape, np_dtype(dtype), low, high)


@register("_random_normal", needs_rng=True, aliases=("normal", "random_normal"))
def random_normal(rng, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return jax.random.normal(rng, shape, np_dtype(dtype)) * scale + loc


@register("_random_gamma", needs_rng=True, aliases=("gamma_sample",))
def random_gamma(rng, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return jax.random.gamma(rng, alpha, shape, np_dtype(dtype)) * beta


@register("_random_exponential", needs_rng=True)
def random_exponential(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(rng, shape, np_dtype(dtype)) / lam


@register("_random_poisson", needs_rng=True)
def random_poisson(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(rng, lam, shape).astype(np_dtype(dtype))


@register("_random_negative_binomial", needs_rng=True)
def random_negative_binomial(rng, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(np_dtype(dtype))


@register("_random_generalized_negative_binomial", needs_rng=True)
def random_gen_negative_binomial(rng, mu=1.0, alpha=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, 1.0 / alpha, shape) * (alpha * mu)
    return jax.random.poisson(k2, lam, shape).astype(np_dtype(dtype))


@register("_random_randint", needs_rng=True, aliases=("randint",))
def random_randint(rng, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(rng, shape, low, high, np_dtype(dtype))


@register("_sample_unique_zipfian", needs_rng=True,
          size_attrs=("range_max",))
def sample_unique_zipfian(rng, range_max=1, shape=()):
    """Unique draws per row from the zipfian (log-uniform) class
    distribution p(k) ∝ log((k+2)/(k+1)) — reference:
    src/operator/random/unique_sample_op.cc (draws until unique). The
    TPU-native version samples WITHOUT replacement in one shot via the
    Gumbel-top-k trick, which is both compile-friendly (static shapes, no
    rejection loop) and exactly equivalent in distribution."""
    rows, k = (shape[0], shape[1]) if len(shape) == 2 else (1, int(shape[0]))
    if rows * range_max <= (1 << 24):
        # exact sampling without replacement: Gumbel-top-k over the class
        # log-probs (equivalent in distribution to draw-until-unique).
        # Covers every case where k is comparable to range_max.
        classes = jnp.arange(range_max)
        logp = jnp.log(jnp.log((classes + 2.0) / (classes + 1.0)))
        g = jax.random.gumbel(rng, (rows, range_max))
        _, idx = jax.lax.top_k(logp[None, :] + g, k)
        return idx.reshape(shape).astype(_device_int_dtype())
    # Huge vocab (sampled-softmax scale, k << range_max): materializing
    # (rows, range_max) would be GBs. Oversample m = 4k+32 i.i.d. zipfian
    # draws via the inverse CDF, deduplicate per row (uniques compacted
    # first), and take the first k uniques. Fewer than k uniques would need
    # >3k+32 collisions among m draws over a range of millions — vanishing
    # probability; in that tail the row keeps duplicates rather than
    # fabricating out-of-distribution fillers (documented divergence from
    # the reference's unbounded draw-until-unique loop).
    m = 4 * k + 32
    u = jax.random.uniform(rng, (rows, m))
    draws = (jnp.exp(u * jnp.log(float(range_max + 1))) - 1.0).astype(_device_int_dtype())
    draws = jnp.clip(draws, 0, range_max - 1)
    s = jnp.sort(draws, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((rows, 1), bool), s[:, 1:] == s[:, :-1]], axis=1)
    order = jnp.argsort(dup, axis=1, stable=True)
    return jnp.take_along_axis(s, order, axis=1)[:, :k] \
        .reshape(shape).astype(_device_int_dtype())


@register("_sample_multinomial", needs_rng=True, aliases=("sample_multinomial", "multinomial"))
def sample_multinomial(rng, data, shape=(), get_prob=False, dtype="int32"):
    """data: (..., k) probabilities; draws `shape` samples per distribution
    (reference: sample_multinomial_op.cc)."""
    n = 1
    for s in shape if isinstance(shape, tuple) else (shape,):
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    samp_shape = (shape,) if isinstance(shape, int) else tuple(shape)
    if data.ndim == 1:
        out = jax.random.categorical(rng, logits, shape=samp_shape or None)
    else:
        out = jax.random.categorical(rng, logits[..., None, :].repeat(max(n, 1), axis=-2), axis=-1)
        out = out.reshape(data.shape[:-1] + samp_shape) if samp_shape else out.reshape(data.shape[:-1])
    return out.astype(np_dtype(dtype))


@register("_shuffle", needs_rng=True, aliases=("shuffle",))
def shuffle(rng, data):
    return jax.random.permutation(rng, data, axis=0)


# --------------------------------------------------------------------------
# token sampling (serving.generate decode loop; SOSP'23 vLLM-style
# sampling surface). One op covers the whole family — greedy is
# temperature<=0, top-k/top-p are nucleus filters on the logits — so a
# mixed decode batch with per-row parameters stays ONE executable
# (`sample_token_logits` takes arrays; the registered op takes the attr
# spelling for nd/symbol callers).
# --------------------------------------------------------------------------

def _top_k_logits(logits, k):
    """Mask logits outside each row's top-k (k<=0 disables; k may be a
    scalar or a per-row array)."""
    v = logits.shape[-1]
    kk = jnp.broadcast_to(jnp.asarray(k, jnp.int32), logits.shape[:-1])
    kk = jnp.clip(jnp.where(kk <= 0, v, kk), 1, v)
    desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    thr = jnp.take_along_axis(desc, (kk - 1)[..., None], axis=-1)
    return jnp.where(logits >= thr, logits, -jnp.inf)


def _top_p_logits(logits, p):
    """Nucleus filter: keep the smallest prefix of descending-probability
    tokens whose mass reaches p (always at least the argmax; p<=0 or
    p>=1 disables). Scalar or per-row p."""
    pp = jnp.broadcast_to(jnp.asarray(p, jnp.float32), logits.shape[:-1])
    pp = jnp.where((pp <= 0.0) | (pp >= 1.0), 1.0, pp)
    desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(desc, axis=-1)
    keep = (jnp.cumsum(probs, axis=-1) - probs) < pp[..., None]
    thr = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits >= thr, logits, -jnp.inf)


def sample_token_logits(rng, logits, temperature=1.0, top_k=0, top_p=1.0):
    """Sample one token id per row of ``logits`` (..., V): greedy argmax
    where temperature<=0, else temperature-scaled categorical over the
    top-k/top-p-filtered distribution. Parameters may be scalars or
    per-row arrays (the decode scheduler batches requests with different
    sampling knobs into one executable). Returns int32 (...)."""
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32),
                         logits.shape[:-1])
    lf = logits.astype(jnp.float32)
    masked = _top_p_logits(_top_k_logits(lf, top_k), top_p)
    scaled = masked / jnp.maximum(t, 1e-6)[..., None]
    drawn = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(t <= 0.0, jnp.argmax(lf, axis=-1),
                     drawn).astype(jnp.int32)


@register("_sample_token", needs_rng=True, aliases=("sample_token",))
def sample_token(rng, data, temperature=1.0, top_k=0, top_p=1.0,
                 dtype="int32"):
    """data: (..., V) logits -> (...) sampled token ids (greedy /
    temperature / top-k / top-p per the attrs; one threefry subkey per
    call, ops/random_ops.py convention)."""
    out = sample_token_logits(rng, data, temperature=float(temperature),
                              top_k=int(top_k), top_p=float(top_p))
    return out.astype(np_dtype(dtype))


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=()):
    h, w = target_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    if transform_type == "affine":
        base = jnp.stack([gx.ravel(), gy.ravel(), jnp.ones(h * w)], axis=0)
        theta = data.reshape((-1, 2, 3))
        out = jnp.einsum("bij,jk->bik", theta, base)
        return out.reshape((-1, 2, h, w))
    # warp: data is (b, 2, h, w) flow
    grid = jnp.stack([gx, gy], axis=0)[None]
    norm = jnp.asarray([(w - 1) / 2.0, (h - 1) / 2.0]).reshape((1, 2, 1, 1))
    return grid + data / norm


@register("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=False):
    """reference: src/operator/bilinear_sampler.cc — sample `data` (NCHW) at
    normalized grid coords (N,2,H',W') in [-1,1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    def sample_one(img, x, y):
        # img: (C,H,W); x,y: (H',W')
        x0 = jnp.floor(x)
        y0 = jnp.floor(y)
        wx = x - x0
        wy = y - y0

        def gather(yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
            valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)).astype(img.dtype)
            return img[:, yi, xi] * valid[None]

        out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[None]
               + gather(y0, x0 + 1) * (wx * (1 - wy))[None]
               + gather(y0 + 1, x0) * ((1 - wx) * wy)[None]
               + gather(y0 + 1, x0 + 1) * (wx * wy)[None])
        return out

    return jax.vmap(sample_one)(data, gx, gy)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(), transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


# --------------------------------------------------------------------------
# vectorized per-distribution sampling (reference: multisample_op.cc —
# `sample_uniform` et al: one distribution per input element, `shape` draws
# from each; output shape = param.shape + shape)
# --------------------------------------------------------------------------

def _multisample(rng, params, shape, draw, dtype):
    shape = tuple(shape) if isinstance(shape, (tuple, list)) else \
        ((int(shape),) if shape else ())
    lead = params[0].shape
    flat = [jnp.reshape(p, (-1,)) for p in params]
    keys = jax.random.split(rng, flat[0].shape[0])
    out = jax.vmap(lambda k, *ps: draw(k, shape, *ps))(keys, *flat)
    return out.reshape(lead + shape).astype(np_dtype(dtype))


@register("_sample_uniform", needs_rng=True, aliases=("sample_uniform",))
def sample_uniform(rng, low, high, shape=(), dtype="float32"):
    return _multisample(
        rng, [low, high], shape,
        lambda k, s, lo, hi: jax.random.uniform(k, s) * (hi - lo) + lo, dtype)


@register("_sample_normal", needs_rng=True, aliases=("sample_normal",))
def sample_normal(rng, mu, sigma, shape=(), dtype="float32"):
    return _multisample(
        rng, [mu, sigma], shape,
        lambda k, s, m, sd: jax.random.normal(k, s) * sd + m, dtype)


@register("_sample_gamma", needs_rng=True, aliases=("sample_gamma",))
def sample_gamma(rng, alpha, beta, shape=(), dtype="float32"):
    return _multisample(
        rng, [alpha, beta], shape,
        lambda k, s, a, b: jax.random.gamma(k, a, s) * b, dtype)


@register("_sample_exponential", needs_rng=True,
          aliases=("sample_exponential",))
def sample_exponential(rng, lam, shape=(), dtype="float32"):
    return _multisample(
        rng, [lam], shape,
        lambda k, s, l: jax.random.exponential(k, s) / l, dtype)


@register("_sample_poisson", needs_rng=True, aliases=("sample_poisson",))
def sample_poisson(rng, lam, shape=(), dtype="float32"):
    return _multisample(
        rng, [lam], shape,
        lambda k, s, l: jax.random.poisson(k, l, s).astype(jnp.float32),
        dtype)


@register("_sample_negative_binomial", needs_rng=True,
          aliases=("sample_negative_binomial",))
def sample_negative_binomial(rng, k, p, shape=(), dtype="float32"):
    def draw(key, s, kk, pp):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, kk, s) * ((1 - pp) / pp)
        return jax.random.poisson(k2, lam, s).astype(jnp.float32)

    return _multisample(rng, [k, p], shape, draw, dtype)


@register("_sample_generalized_negative_binomial", needs_rng=True,
          aliases=("sample_generalized_negative_binomial", "sample_gnb"))
def sample_generalized_negative_binomial(rng, mu, alpha, shape=(),
                                         dtype="float32"):
    def draw(key, s, m, a):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, 1.0 / a, s) * (a * m)
        return jax.random.poisson(k2, lam, s).astype(jnp.float32)

    return _multisample(rng, [mu, alpha], shape, draw, dtype)
