"""Fused RNN op as an XLA scan.

TPU-native equivalent of the reference's monolithic RNN operator
(src/operator/rnn-inl.h:162 RNNParam; cuDNN path cudnn_rnn-inl.h, native loops
rnn_impl.h). Instead of cuDNN's fused kernel we express each layer as a
`lax.scan` whose step does one MXU matmul per gate-block — XLA pipelines the
time steps and keeps weights resident. Parameter packing is kept bit-compatible
with the reference/cuDNN flat-vector layout (all weights layer-major then all
biases; gate order LSTM=(i,f,g,o), GRU=(r,z,n)) so checkpoints round-trip.

Layouts: data (T, B, I) seq-major like the reference; states (L*D, B, H).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total flat parameter count (reference: rnn-inl.h GetParamSize)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (in_sz + state_size + 2)
    return size


def _unpack(params, num_layers, input_size, state_size, bidirectional, mode):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    H, G = state_size, gates
    weights = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * dirs
        layer_w = []
        for d in range(dirs):
            wx = lax.dynamic_slice(params, (off,), (G * H * in_sz,)).reshape(G * H, in_sz)
            off += G * H * in_sz
            wh = lax.dynamic_slice(params, (off,), (G * H * H,)).reshape(G * H, H)
            off += G * H * H
            layer_w.append([wx, wh, None, None])
        weights.append(layer_w)
    for layer in range(num_layers):
        for d in range(dirs):
            bx = lax.dynamic_slice(params, (off,), (G * H,))
            off += G * H
            bh = lax.dynamic_slice(params, (off,), (G * H,))
            off += G * H
            weights[layer][d][2] = bx
            weights[layer][d][3] = bh
    return weights


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, gx, wh, bh):
            h, c = carry
            g = gx + jnp.dot(h, wh.T) + bh
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            new_c = f * c + i * jnp.tanh(gg)
            new_h = o * jnp.tanh(new_c)
            return (new_h, new_c), new_h
    elif mode == "gru":
        def step(carry, gx, wh, bh):
            h, _ = carry
            hh = jnp.dot(h, wh.T) + bh
            xr, xz, xn = jnp.split(gx, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            new_h = (1 - z) * n + z * h
            return (new_h, new_h), new_h
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def step(carry, gx, wh, bh):
            h, _ = carry
            new_h = act(gx + jnp.dot(h, wh.T) + bh)
            return (new_h, new_h), new_h
    return step


def _pallas_lstm_enabled():
    """Fused Pallas LSTM layer: default on for TPU; MXTPU_PALLAS_LSTM=1
    forces it elsewhere (interpret mode), =0 disables everywhere."""
    from .. import env as _env_mod

    env = _env_mod.get("MXTPU_PALLAS_LSTM")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def _run_layer(x, wx, wh, bx, bh, h0, c0, mode, reverse=False):
    """x: (T,B,I) -> (T,B,H). Pre-computes the input projections for the whole
    sequence as one big MXU matmul, then runs the recurrence — as a fused
    Pallas kernel for LSTM on TPU (weights VMEM-resident across the whole
    time loop; see pallas_kernels.lstm_layer), else as a lax.scan whose step
    does the (small) recurrent matmul."""
    H = h0.shape[-1]
    if mode == "lstm" and _pallas_lstm_enabled():
        from . import pallas_kernels

        if pallas_kernels.lstm_layer_fits(
                x.shape[1], H, jnp.dtype(x.dtype).itemsize):
            gx_all = jnp.dot(x, wx.T) + (bx + bh)  # both biases additive
            if reverse:
                gx_all = jnp.flip(gx_all, axis=0)
            ys, hT, cT = pallas_kernels.lstm_layer(gx_all, wh, h0, c0)
            if reverse:
                ys = jnp.flip(ys, axis=0)
            return ys, hT, cT
    gx_all = jnp.dot(x, wx.T) + bx  # (T,B,G*H) — single large matmul
    step_fn = _cell_step(mode, H)

    def scan_step(carry, gx):
        return step_fn(carry, gx, wh, bh)

    (hT, cT), ys = lax.scan(scan_step, (h0, c0), gx_all, reverse=reverse)
    return ys, hT, cT


@register("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss", "_contrib_ctc_loss"))
def ctc_loss(pred, label, pred_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """CTC forward algorithm as a lax.scan (reference: warp-ctc via
    src/operator/contrib/ctc_loss.cc; blank index 0 for blank_label='first').

    pred: (T, B, C) raw activations (softmax applied internally, matching the
    reference). label: (B, L) class indices (padded). Returns per-sample loss."""
    T, B, C = pred.shape
    L = label.shape[1]
    # blank_label='first': blank=0, labels 1-based, padding 0 (reference
    # symbolic default). blank_label='last': blank=C-1, labels 0-based,
    # padding -1 (what the reference gluon CTCLoss wrapper passes).
    blank = 0 if blank_label == "first" else C - 1
    lab_raw = label.astype(jnp.int32)
    # clamp padding (-1 under 'last') to blank so gathers stay in range;
    # padded positions sit past 2*l_len and never reach the final alphas
    lab = jnp.where(lab_raw < 0, blank, lab_raw)
    logp = jax.nn.log_softmax(pred.astype(jnp.float32), axis=-1)
    S = 2 * L + 1
    # extended label sequence with interleaved blanks
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    NEG = jnp.asarray(-1e30, jnp.float32)

    # gather per-position class log-probs: (T,B,C) indexed by (B,S) → (T,B,S)
    ext_logp = jnp.take_along_axis(logp, jnp.broadcast_to(ext[None], (T, B, S)),
                                   axis=2)

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (~same_as_prev2)

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(ext_logp[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(ext_logp[0, :, 1])

    def lse(a, b):
        m = jnp.maximum(a, b)
        return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))

    def step(alpha, lp_t):
        shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        acc = lse(alpha, shift1)
        acc = jnp.where(can_skip, lse(acc, shift2), acc)
        new_alpha = acc + lp_t
        return new_alpha, new_alpha

    _, alphas = lax.scan(step, alpha0, ext_logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)

    t_idx = (pred_lengths.astype(jnp.int32) - 1) if (use_data_lengths and pred_lengths is not None) \
        else jnp.full((B,), T - 1, jnp.int32)
    if use_label_lengths and label_lengths is not None:
        l_len = label_lengths.astype(jnp.int32)
    elif blank == 0:
        # 'first': labels 1-based, 0 is padding
        l_len = jnp.sum((lab_raw != 0).astype(jnp.int32), axis=1)
    else:
        # 'last': labels 0-based, -1 is padding (reference ctc_loss.cc
        # padding_mask for blank_label='last')
        l_len = jnp.sum((lab_raw >= 0).astype(jnp.int32), axis=1)
    final = alphas[t_idx, jnp.arange(B)]  # (B, S)
    end1 = jnp.take_along_axis(final, (2 * l_len)[:, None], axis=1)[:, 0]
    end2 = jnp.take_along_axis(final, jnp.maximum(2 * l_len - 1, 0)[:, None], axis=1)[:, 0]
    # empty label: the only path is all-blank (end1); the clamped end2 index
    # would double-count it
    end2 = jnp.where(l_len == 0, NEG, end2)
    return -lse(end1, end2)


@register("RNN", num_outputs=-1, needs_rng=True)
def rnn(rng, data, parameters, state, state_cell=None, state_size=0, num_layers=1,
        bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
        projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, is_train=False):
    T, B, I = data.shape
    H = state_size
    dirs = 2 if bidirectional else 1
    weights = _unpack(parameters, num_layers, I, H, bidirectional, mode)
    x = data
    h_finals = []
    c_finals = []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            wx, wh, bx, bh = weights[layer][d]
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if (mode == "lstm" and state_cell is not None) else jnp.zeros_like(h0)
            ys, hT, cT = _run_layer(x, wx, wh, bx, bh, h0, c0, mode, reverse=(d == 1))
            outs.append(ys)
            h_finals.append(hT)
            c_finals.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if is_train and p > 0.0 and layer < num_layers - 1:
            sub = jax.random.fold_in(rng, layer)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape).astype(x.dtype)
            x = x * mask / (1.0 - p)
    out = x
    if mode == "lstm" and lstm_state_clip_min is not None:
        h_finals = [jnp.clip(h, lstm_state_clip_min, lstm_state_clip_max) for h in h_finals]
    if not state_outputs:
        return (out,)
    hN = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        cN = jnp.stack(c_finals, axis=0)
        return (out, hN, cN)
    return (out, hN)


@register("_rnn_state_zeros")
def rnn_state_zeros(data, state_shape=()):
    """Zero initial RNN state with the batch dim taken from `data`
    (symbolic begin_state support: the reference writes shape (0, H) and
    lets nnvm shape inference fill the batch — here the batch rides the
    data symbol so jax.eval_shape can infer it; mx.rnn BaseRNNCell)."""
    return jnp.zeros((data.shape[0],) + tuple(state_shape), data.dtype)


@register("_rnn_fused_state_zeros")
def rnn_fused_state_zeros(data, num_directions_layers=1, state_size=0,
                          batch_axis=1):
    """Zero fused-RNN state (L*dirs, B, H); B comes from `data` at
    `batch_axis` — 1 for the merged (T, B, I) unroll input, 0 when the
    reference is a per-step (B, C) symbol (mx.rnn FusedRNNCell inside a
    SequentialRNNCell, whose begin_state runs before the fused merge)."""
    return jnp.zeros((num_directions_layers, data.shape[batch_axis],
                      state_size), data.dtype)
