"""Fused optimizer update ops.

Reference: src/operator/optimizer_op.cc (+contrib/adamw.cc) — SGD/Adam/etc as
single fused kernels mutating the weight in place. TPU-native: each update is
a pure function returning (new_weight, *new_states); the dispatch layer swaps
the weight NDArray's buffer (functional "donation" — XLA aliases the input
buffer when the update runs inside a jit with donated args). All updates are
single fused XLA kernels: grad rescale, clip, wd, momentum and the write are
one HBM pass."""
from __future__ import annotations

from . import register

import jax.numpy as jnp
from jax import lax


def _prep(grad, rescale_grad, clip_gradient, wd, weight):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight.astype(jnp.float32)


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    return (weight.astype(jnp.float32) + new_mom).astype(weight.dtype), new_mom


@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight32)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("nag_mom_update", num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    w = weight.astype(jnp.float32) - lr * (g + momentum * new_mom)
    return w.astype(weight.dtype), new_mom


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight.astype(jnp.float32) - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return w.astype(weight.dtype), new_mean, new_var


@register("adamw_update", num_outputs=3, aliases=("_contrib_adamw_update",))
def adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=0.001, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, clip_gradient=-1.0):
    """reference: src/operator/contrib/adamw.cc — decoupled weight decay."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight.astype(jnp.float32) - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                                            + wd * weight.astype(jnp.float32))
    return w.astype(weight.dtype), new_mean, new_var


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(new_n) + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w.astype(weight.dtype), new_n


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_state + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    w = weight.astype(jnp.float32) + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w.astype(weight.dtype), new_n, new_g, new_delta


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w32 = weight.astype(jnp.float32)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * w32
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(w32),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return w.astype(weight.dtype), new_z, new_n


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    g = _prep(grad, rescale_grad, clip_grad, wd, weight)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight.astype(jnp.float32)
    w = -new_z / d_t
    return w.astype(weight.dtype), d_t, new_v, new_z


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, 0.0, weight)
    w = weight.astype(jnp.float32) * (1 - lr * wd) - lr * jnp.sign(g)
    return w.astype(weight.dtype)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.9, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1 - momentum) * g
    w = weight.astype(jnp.float32) * (1 - lr * wd_lh) + lr * jnp.sign(new_mom)
    return w.astype(weight.dtype), new_mom


@register("adagrad_update", num_outputs=2, aliases=("_sparse_adagrad_update",))
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_hist = history + jnp.square(g)
    w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(new_hist) + epsilon)
    return w.astype(weight.dtype), new_hist


@register("adadelta_update", num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    w = weight.astype(jnp.float32) - delta
    return w.astype(weight.dtype), new_acc_g, new_acc_delta


@register("multi_sgd_update", num_outputs=-1)
def multi_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0, clip_gradient=-1.0,
                     num_weights=1):
    """Aggregated update (reference: optimizer_op.cc multi_sgd) — one fused
    launch updating many weights; XLA compiles the whole batch into one
    executable, amortizing dispatch like the reference's aggregated kernels.
    Inputs are INTERLEAVED per weight — (w0, g0, w1, g1, ...) — matching the
    reference's MultiSGDUpdate data layout."""
    weights = args[0::2]
    grads = args[1::2]
    outs = []
    for i in range(num_weights):
        g = _prep(grads[i], rescale_grad, clip_gradient, wds[i], weights[i])
        outs.append((weights[i].astype(jnp.float32) - lrs[i] * g).astype(weights[i].dtype))
    return tuple(outs)


@register("multi_sgd_mom_update", num_outputs=-1)
def multi_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    # interleaved (w0, g0, m0, w1, g1, m1, ...) — reference layout
    weights = args[0::3]
    grads = args[1::3]
    moms = args[2::3]
    outs = []
    new_moms = []
    for i in range(num_weights):
        g = _prep(grads[i], rescale_grad, clip_gradient, wds[i], weights[i])
        nm = momentum * moms[i] - lrs[i] * g
        new_moms.append(nm)
        outs.append((weights[i].astype(jnp.float32) + nm).astype(weights[i].dtype))
    return tuple(outs) + tuple(new_moms)
