"""Linear-algebra ops (reference: src/operator/tensor/la_op.cc — potrf, potri,
gemm, gemm2, trmm, trsm, sumlogdiag, syrk, gelqf, syevd). Batched via leading
dims; XLA lowers these to its native decomposition/triangular-solve HLOs."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import register


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register("_linalg_gemm", aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + beta * C


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A):
    ident = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_l = jax.scipy.linalg.solve_triangular(A, ident, lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@register("_linalg_trsm", aliases=("linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    if rightside:
        # solve X M = alpha B via M^T X^T = alpha B^T (transpose flips triangularity)
        M = _t(A, transpose)
        lower_eff = lower != transpose
        Xt = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(M, -1, -2), jnp.swapaxes(alpha * B, -1, -2), lower=not lower_eff)
        return jnp.swapaxes(Xt, -1, -2)
    return jax.scipy.linalg.solve_triangular(A, alpha * B, lower=lower,
                                             trans=1 if transpose else 0)


@register("_linalg_trmm", aliases=("linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    At = _t(A, transpose)
    return alpha * (jnp.matmul(B, At) if rightside else jnp.matmul(At, B))


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("_linalg_syrk", aliases=("linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    At = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(At, A) if transpose else jnp.matmul(A, At))


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_makediag", aliases=("linalg_makediag",))
def linalg_makediag(A, offset=0):
    return jax.vmap(jnp.diag, in_axes=0)(A.reshape((-1, A.shape[-1]))).reshape(
        A.shape[:-1] + (A.shape[-1], A.shape[-1]))


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_inverse", aliases=("linalg_inverse",))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_det", aliases=("linalg_det",))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("_linalg_slogdet", aliases=("linalg_slogdet",), num_outputs=2)
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet
