"""Scoped symbol attributes.

TPU-native equivalent of the reference's `python/mxnet/attribute.py`
(`AttrScope`: a with-scope whose attributes are stamped onto every symbol
created inside it — used for ctx groups, lr_mult, and the model-parallel
`group2ctx` annotation path, reference attribute.py:25).
"""
from __future__ import annotations

import threading

from .base import string_types

__all__ = ["AttrScope", "current"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [AttrScope()]
    return _state.stack


class AttrScope:
    """Attribute manager for symbol scoping (reference: attribute.py:25).

    with AttrScope(ctx_group='dev1', lr_mult='0.5'):
        w = mx.sym.var('w')   # w carries both attributes
    """

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, string_types):
                raise ValueError("attributes must be strings")
        self._attr = kwargs

    def get(self, attr=None):
        """Merge scope attributes into `attr` (user-provided wins —
        reference: attribute.py:49)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        stack = _stack()
        merged = dict(stack[-1]._attr)
        merged.update(self._attr)
        scope = AttrScope()
        scope._attr = merged
        stack.append(scope)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def current():
    """The innermost active AttrScope."""
    return _stack()[-1]
