"""Legacy `mxnet.torch` namespace (reference: python/mxnet/torch.py — the
lua-Torch TH/THNN op wrapper). Lua Torch is long dead; this name now
fronts the PyTorch bridge (`mxnet_tpu.torch_bridge`): zero-copy DLPack
exchange plus tape-integrated torch function calls, which subsumes what
the TH wrapper provided (calling torch kernels on mxnet arrays)."""
from .torch_bridge import *  # noqa: F401,F403
from .torch_bridge import __all__  # noqa: F401
