"""mxnet_tpu: a TPU-native deep-learning framework with the capabilities of
Apache MXNet (incubating).

This is a ground-up rebuild of the reference (/root/reference, MXNet ~1.4)
for TPU hardware: the compute path is JAX/XLA (+Pallas kernels), the
execution model is compiled-graph-first (jit/pjit over a device Mesh), and
the distributed layer is XLA collectives over ICI/DCN instead of
ps-lite/NCCL. See SURVEY.md at the repo root for the full component mapping.

Public surface mirrors `import mxnet as mx`:
    mx.nd, mx.sym, mx.gluon, mx.autograd, mx.optimizer, mx.metric, mx.io,
    mx.kv/kvstore, mx.context/cpu/gpu/tpu, mx.init(ializer), mx.mod(ule),
    mx.random, mx.profiler, mx.lr_scheduler, mx.callback, mx.test_utils
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import env
from .base import (MXNetError, enable_persistent_compile_cache,
                   honor_explicit_cpu_platform)

# before any backend initializes: a sitecustomize PJRT hook may have
# clobbered the documented `JAX_PLATFORMS=cpu` contract (see the helper)
honor_explicit_cpu_platform()
enable_persistent_compile_cache()
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd

# subsystem imports are appended as the build widens (round-1 scaffold keeps
# this list in sync with the modules that exist)
_SUBMODULES = [
    "telemetry",
    "optimizer", "initializer", "lr_scheduler", "metric", "symbol", "executor",
    "module", "io", "data", "recordio", "image", "kvstore", "gluon", "callback",
    "model", "profiler", "runtime", "test_utils", "visualization", "monitor",
    "parallel", "attribute", "name", "operator", "contrib", "rtc",
    "torch_bridge", "registry", "log", "libinfo", "util",
    "kvstore_server", "executor_manager", "rnn", "serving",
    # legacy-name shims (reference top-level module map)
    "misc", "ndarray_doc", "symbol_doc", "torch",
]
import importlib as _importlib
import os as _os

for _m in _SUBMODULES:
    if _os.path.exists(_os.path.join(_os.path.dirname(__file__), _m + ".py")) or \
       _os.path.isdir(_os.path.join(_os.path.dirname(__file__), _m)):
        globals()[_m] = _importlib.import_module("." + _m, __name__)

# reference __init__.py aliases `torch` as `th` too
if "torch" in globals():
    th = globals()["torch"]

if "kvstore_server" in globals() and _os.environ.get("DMLC_ROLE") in (
        "server", "scheduler"):
    # reference parity: mxnet/__init__ runs the PS server loop for
    # server-role processes; ours logs the collectives architecture note
    # and exits so reference launch scripts keep a correct worker count
    kvstore_server._maybe_exit_non_worker()  # noqa: F821

# telemetry-configured processes (MXTPU_TELEMETRY_DIR set — launched jobs)
# get the SIGUSR1 flight-recorder dump handler from import time, so even a
# hang BEFORE the first training step (rendezvous, compile) is diagnosable
# via the launcher's SIGUSR1-then-SIGTERM teardown
if "telemetry" in globals() and env.is_set("MXTPU_TELEMETRY_DIR"):
    telemetry.install_signal_handler()  # noqa: F821

if "symbol" in globals():
    sym = symbol  # noqa: F821
    Symbol = symbol.Symbol  # noqa: F821
if "module" in globals():
    mod = module  # noqa: F821
if "kvstore" in globals():
    kv = kvstore  # noqa: F821
if "initializer" in globals():
    init = initializer  # noqa: F821
if "visualization" in globals():
    viz = visualization  # noqa: F821
if "attribute" in globals():
    AttrScope = attribute.AttrScope  # noqa: F821
