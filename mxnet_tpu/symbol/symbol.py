"""Symbol — the declarative graph-building API.

Reference: python/mxnet/symbol/symbol.py (~3k LoC ctypes wrapper over the
nnvm graph C API: compose :?, infer_shape, bind/simple_bind, tojson/load).

TPU-native design: the graph is a tiny Python DAG of `_Node`s over the SAME
op registry the imperative path uses (mxnet_tpu/ops). There is no separate
symbolic kernel path and no NNVM pass pipeline — binding a Symbol hands the
whole graph to `jax.jit`, where XLA performs what the reference's
GraphExecutor::Init did by hand (shape inference, memory planning, fusion,
placement — graph_executor.cc:321, SURVEY §3.5). Gradients come from
`jax.vjp` of the interpreted graph instead of the nnvm MXGradient pass
(src/nnvm/gradient.cc:271).
"""
from __future__ import annotations

import json

import numpy as _np

from ..base import MXNetError
from .. import ops as _ops

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "pow", "maximum", "minimum", "ones_like", "zeros_like"]

class _Node:
    """One graph node: a variable (op is None) or an op application."""

    __slots__ = ("op", "name", "attrs", "inputs", "aux_slots", "_shape", "_dtype")

    def __init__(self, op, name, attrs=None, inputs=None, aux_slots=()):
        self.op = op                     # op name in the registry, or None
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs or [])  # [(Node, out_index)]
        self.aux_slots = tuple(aux_slots)  # indices into `inputs` that are aux
        self._shape = None                # declared shape, for variables
        self._dtype = None

    @property
    def is_var(self):
        return self.op is None

    def num_outputs(self):
        if self.is_var:
            return 1
        od = _ops.get(self.op)
        if od.num_outputs > 0:
            return od.num_outputs
        if od.num_outputs_fn is not None:
            # variadic arity resolved from this node's attrs (e.g. Proposal
            # grows a score output under output_score=True)
            return max(1, od.num_outputs_fn(self.attrs))
        return 1

    def visible_outputs(self):
        if self.is_var:
            return 1
        od = _ops.get(self.op)
        if od.num_outputs > 0:
            return max(1, od.visible_outputs)
        return self.num_outputs()


class Symbol:
    """A handle on one or more graph outputs (reference: symbol.py Symbol)."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)    # [(Node, out_index)]

    # -- identity ----------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        if len(self._outputs) == 1:
            return "<Symbol %s>" % self._outputs[0][0].name
        return "<Symbol group [%s]>" % ", ".join(n.name for n, _ in self._outputs)

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self.list_outputs())

    def __getitem__(self, index):
        outs = self.list_outputs()
        if isinstance(index, str):
            if index not in outs:
                raise MXNetError("output '%s' not found in %s" % (index, outs))
            index = outs.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        # graph nodes are immutable once composed; sharing them is safe
        return Symbol(list(self._outputs))

    # -- graph walking -----------------------------------------------------
    def _topo(self):
        # DFS post-order visiting inputs left-to-right: variables appear in
        # the order the graph consumes them (data before weights before the
        # next layer's weights), matching the reference's nnvm IndexedGraph
        # argument ordering
        order, seen = [], set()
        stack = [(n, False) for n, _ in reversed(self._outputs)]
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for m, _ in reversed(node.inputs):
                if id(m) not in seen:
                    stack.append((m, False))
        return order

    def list_arguments(self):
        """Variable names feeding the graph, minus aux states
        (reference: symbol.py list_arguments)."""
        aux = set(self._aux_nodes())
        return [n.name for n in self._topo() if n.is_var and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_nodes()
        order = [id(n) for n in self._topo()]
        return [n.name for n in sorted(
            {i: n for i, n in aux.items()}.values(),
            key=lambda n: order.index(id(n)))]

    def _aux_nodes(self):
        """Vars wired into aux input slots (BatchNorm moving stats...)."""
        aux = {}
        for node in self._topo():
            for slot in node.aux_slots:
                src, _ = node.inputs[slot]
                if src.is_var:
                    aux[id(src)] = src
        return aux

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_var:
                names.append(node.name)
            elif node.visible_outputs() == 1:
                names.append(node.name + "_output")
            else:
                names.append("%s_output%d" % (node.name, idx))
        return names

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_var]

    def get_internals(self):
        """Every node output as a group (reference: symbol.py get_internals)."""
        outs = []
        for node in self._topo():
            for i in range(node.visible_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        kids = []
        for node, _ in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    @property
    def attrs(self):
        if len(self._outputs) == 1:
            return dict(self._outputs[0][0].attrs)
        return {}

    def attr(self, key):
        attrs = self._outputs[0][0].attrs
        if key in attrs:
            return attrs[key]
        return attrs.get("__%s__" % key.strip("_"))

    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.attrs.items()}
                for n in self._topo() if n.attrs}

    # -- composition helpers ----------------------------------------------
    def _binop(self, other, opname, reverse=False):
        from . import _functions

        f = _functions[opname]
        if isinstance(other, Symbol):
            return f(other, self) if reverse else f(self, other)
        scalar_ops = {"broadcast_add": "_plus_scalar",
                      "broadcast_sub": "_rminus_scalar" if reverse else "_minus_scalar",
                      "broadcast_mul": "_mul_scalar",
                      "broadcast_div": "_rdiv_scalar" if reverse else "_div_scalar",
                      "broadcast_power": "_rpower_scalar" if reverse else "_power_scalar",
                      "broadcast_mod": "_rmod_scalar" if reverse else "_mod_scalar",
                      "broadcast_greater": "_lesser_scalar" if reverse else "_greater_scalar",
                      "broadcast_lesser": "_greater_scalar" if reverse else "_lesser_scalar",
                      "broadcast_greater_equal": "_lesser_equal_scalar" if reverse else "_greater_equal_scalar",
                      "broadcast_lesser_equal": "_greater_equal_scalar" if reverse else "_lesser_equal_scalar",
                      "broadcast_equal": "_equal_scalar",
                      "broadcast_not_equal": "_not_equal_scalar"}
        sop = scalar_ops.get(opname)
        if sop is None:
            raise MXNetError("unsupported scalar operand for %s" % opname)
        return _functions[sop](self, scalar=float(other))

    def __add__(self, other):
        return self._binop(other, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", reverse=True)

    def __mod__(self, other):
        return self._binop(other, "broadcast_mod")

    def __pow__(self, other):
        return self._binop(other, "broadcast_power")

    def __neg__(self):
        return self.__mul__(-1.0)

    def __eq__(self, other):  # noqa: comparison builds graph, like reference
        return self._binop(other, "broadcast_equal")

    def __ne__(self, other):
        return self._binop(other, "broadcast_not_equal")

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal")

    def __hash__(self):
        return id(self)

    def __getattr__(self, name):
        # sym.reshape(...)-style method calls on single-output symbols
        from . import _functions

        if name.startswith("_"):
            raise AttributeError(name)
        f = _functions.get(name)
        if f is None:
            raise AttributeError("Symbol has no attribute/op '%s'" % name)

        def call(*args, **kwargs):
            return f(self, *args, **kwargs)

        return call

    # -- interpretation ----------------------------------------------------
    def _interpret(self, values, is_train=False, rng_key=None):
        """Evaluate the graph on raw jax arrays.

        values: {var_name: array}. Returns (outputs, aux_updates) where
        aux_updates maps aux var name -> new array (BatchNorm moving stats:
        the functional form of the reference's in-place aux mutation).
        """
        import jax

        computed = {}
        aux_updates = {}
        key_iter = [rng_key]

        def next_subkey():
            if key_iter[0] is None:
                from .. import random as _random

                key_iter[0] = _random.next_key()
            key, sub = jax.random.split(key_iter[0])
            key_iter[0] = key
            return sub

        for node in self._topo():
            if node.is_var:
                if node.name not in values:
                    raise MXNetError("missing value for variable '%s'" % node.name)
                computed[id(node)] = (values[node.name],)
                continue
            opdef = _ops.get(node.op)
            in_arrays = tuple(computed[id(src)][idx] for src, idx in node.inputs)
            # user/scope attributes (`__key__`) are graph metadata, not op params
            attrs = {k: v for k, v in node.attrs.items()
                     if not (k.startswith("__") and k.endswith("__"))}
            from ..ndarray.ndarray import _takes_is_train

            if _takes_is_train(opdef):
                attrs.setdefault("is_train", is_train)
            # the generated caller records parameter names when inputs bind
            # to non-leading slots (optional array args skipped); honor them
            bind_names = node.attrs.get("__input_names__")
            if bind_names is not None and len(bind_names) == len(in_arrays):
                kw = dict(zip(bind_names, in_arrays))
                if opdef.needs_rng:
                    out = opdef.fn(next_subkey(), **kw, **attrs)
                else:
                    out = opdef.fn(**kw, **attrs)
            else:
                if opdef.needs_rng:
                    in_arrays = (next_subkey(),) + in_arrays
                out = opdef.fn(*in_arrays, **attrs)
            out = tuple(out) if isinstance(out, (tuple, list)) else (out,)
            computed[id(node)] = out
            # hidden trailing outputs update the trailing aux inputs
            n_aux = len(out) - node.visible_outputs()
            if n_aux > 0:
                aux_srcs = [node.inputs[s][0] for s in node.aux_slots]
                for src, new in zip(aux_srcs[-n_aux:], out[-n_aux:]):
                    if src.is_var:
                        aux_updates[src.name] = new
        outputs = [computed[id(node)][idx] for node, idx in self._outputs]
        return outputs, aux_updates

    # -- evaluation convenience -------------------------------------------
    def eval(self, ctx=None, **kwargs):
        """Evaluate with NDArray kwargs (reference: symbol.py eval)."""
        from .. import context as ctx_mod
        from ..ndarray import NDArray

        ctx = ctx or ctx_mod.current_context()
        values = {k: (v._data if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
        outs, _ = self._interpret(values)
        return [NDArray(o, ctx=ctx) for o in outs]

    def eval_with(self, values):
        from ..ndarray import NDArray

        ctx = None
        raw = {}
        for k, v in values.items():
            if isinstance(v, NDArray):
                ctx = ctx or v.context
                raw[k] = v._data
            else:
                raw[k] = v
        outs, _ = self._interpret(raw)
        res = [NDArray(o, ctx=ctx) for o in outs]
        return res[0] if len(res) == 1 else res

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes) — reference symbol.py
        infer_shape. Unknown weight shapes are filled from per-op rules
        (see register._ARG_SHAPE_RULES), then shapes propagate forward via
        jax.eval_shape (XLA abstract evaluation replaces the nnvm
        InferShape pass, src/executor/infer_graph_attr_pass.cc)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        from .register import infer_var_shapes

        known = {}
        if args:
            arg_names = self.list_arguments()
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        shapes = infer_var_shapes(self, known)   # fills weights from op rules

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        missing = [n for n in arg_names + aux_names if n not in shapes]
        if missing and not partial:
            raise MXNetError("infer_shape: cannot infer shapes for %s" % missing)

        # forward-propagate to outputs with abstract eval
        try:
            structs = {n: jax.ShapeDtypeStruct(shapes[n], jnp.float32)
                       for n in shapes}
            # abstract eval only: pass a concrete dummy key so RNG ops
            # don't split the GLOBAL key chain inside the trace (that
            # would store a tracer in random's thread state — leak)
            dummy_key = jax.ShapeDtypeStruct((2,), jnp.uint32)
            out_struct = jax.eval_shape(
                lambda vals, k: self._interpret(vals, is_train=True,
                                                rng_key=k)[0],
                structs, dummy_key)
            out_shapes = [tuple(o.shape) for o in out_struct]
        except Exception:
            if partial:
                out_shapes = [None] * len(self._outputs)
            else:
                raise
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dtype = _np.float32
        for a in list(args) + list(kwargs.values()):
            if a is not None:
                dtype = a
                break
        return ([dtype] * len(arg_names),
                [dtype] * len(self._outputs),
                [dtype] * len(self.list_auxiliary_states()))

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate argument/gradient/aux arrays from inferred shapes and
        bind (reference: graph_executor.cc:1694 SimpleBind)."""
        from .. import context as ctx_mod
        from ..executor import Executor
        from ..ndarray import zeros

        ctx = ctx or ctx_mod.current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError("simple_bind: cannot infer shape for %s" % missing)

        shared = {}
        if shared_exec is not None:
            shared = dict(zip(shared_exec._arg_names, shared_exec.arg_arrays))
        if shared_buffer is not None:
            shared.update(shared_buffer)
        args = []
        for n, s in zip(arg_names, arg_shapes):
            if n in shared and tuple(shared[n].shape) == tuple(s):
                args.append(shared[n])
            else:
                args.append(zeros(s, ctx=ctx))
                if shared_buffer is not None:
                    shared_buffer[n] = args[-1]
        req = grad_req if isinstance(grad_req, (str, dict)) else "write"
        args_grad = {}
        for n, s in zip(arg_names, arg_shapes):
            r = req if isinstance(req, str) else req.get(n, "write")
            if r != "null":
                args_grad[n] = zeros(s, ctx=ctx)
        aux_states = [zeros(s, ctx=ctx) for s in aux_shapes]
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """reference: graph_executor.cc:1726 Bind."""
        from ..executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    # -- gradient ----------------------------------------------------------
    def gradient(self, wrt):
        raise MXNetError("symbolic gradient graphs are not materialized; "
                         "Executor.backward computes gradients via jax.vjp "
                         "(TPU-native divergence from nnvm/gradient.cc)")

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """nnvm-style JSON (reference: symbol.py tojson; legacy_json_util.cc)."""
        nodes = self._topo()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        out = {
            "nodes": [
                {
                    "op": n.op or "null",
                    "name": n.name,
                    "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                              for k, v in n.attrs.items()},
                    "inputs": [[node_ids[id(src)], idx, 0] for src, idx in n.inputs],
                    "aux_slots": list(n.aux_slots),
                }
                for n in nodes
            ],
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_var],
            "heads": [[node_ids[id(node)], idx, 0] for node, idx in self._outputs],
            "mxnet_tpu_version": 1,
        }
        return json.dumps(out, indent=2)

    def save(self, fname):
        from ..base import atomic_writer

        # atomic (temp + fsync + rename): a kill mid-save never truncates an
        # existing prefix-symbol.json (same guarantee as nd.save)
        with atomic_writer(fname, "w") as f:
            f.write(self.tojson())

    # debugging
    def debug_str(self):
        lines = []
        for n in self._topo():
            if n.is_var:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (s.name, i) for s, i in n.inputs)
                lines.append("Op:%s, Name=%s, Inputs=[%s]" % (n.op, n.name, ins))
        return "\n".join(lines)


def _wrap_attr_keys(attr):
    """User/scope attributes are stored `__key__`-wrapped so they can never
    collide with op parameters (reference keeps user attrs in the same nnvm
    dict under the raw key; our op attrs feed jax fns as kwargs, hence the
    namespacing)."""
    return {(k if (k.startswith("__") and k.endswith("__")) else "__%s__" % k): v
            for k, v in attr.items()}


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference: symbol.py var/Variable); merges
    the active AttrScope's attributes (reference: attribute.py:49)."""
    from .. import attribute

    node = _Node(None, name)
    node._shape = tuple(shape) if shape is not None else None
    node._dtype = dtype
    attr = attribute.current().get(attr)
    if attr:
        node.attrs.update(_wrap_attr_keys(attr))
    if lr_mult is not None:
        node.attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        node.attrs["__wd_mult__"] = wd_mult
    if init is not None:
        node.attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    node.attrs.update(kwargs)
    return Symbol([(node, 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for nd_ in data["nodes"]:
        op = None if nd_["op"] == "null" else nd_["op"]
        attrs = {}
        for k, v in nd_.get("attrs", {}).items():
            try:
                attrs[k] = json.loads(v)
            except (json.JSONDecodeError, TypeError):
                attrs[k] = v
        node = _Node(op, nd_["name"], attrs,
                     [(nodes[i], oi) for i, oi, _ in nd_.get("inputs", [])],
                     tuple(nd_.get("aux_slots", [])))
        nodes.append(node)
    return Symbol([(nodes[i], oi) for i, oi, _ in data["heads"]])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# a few free functions the reference exposes at mxnet.symbol level
def pow(base, exp):
    return base ** exp


def maximum(lhs, rhs):
    from . import _functions

    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _functions["broadcast_maximum"](lhs, rhs)
    s, other = (lhs, rhs) if isinstance(lhs, Symbol) else (rhs, lhs)
    return _functions["_maximum_scalar"](s, scalar=float(other))


def minimum(lhs, rhs):
    from . import _functions

    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _functions["broadcast_minimum"](lhs, rhs)
    s, other = (lhs, rhs) if isinstance(lhs, Symbol) else (rhs, lhs)
    return _functions["_minimum_scalar"](s, scalar=float(other))


def ones_like(data):
    from . import _functions

    return _functions["ones_like"](data)


def zeros_like(data):
    from . import _functions

    return _functions["zeros_like"](data)
