"""mxnet_tpu.symbol — declarative graph API (reference: python/mxnet/symbol).

The graph is a Python DAG over the shared op registry; binding compiles the
whole graph with jax.jit (XLA = the pass pipeline). See symbol.py docstring.
"""
from .symbol import (Symbol, var, Variable, Group, load, load_json, pow,
                     maximum, minimum, ones_like, zeros_like)
from . import register as _register

_functions = _register.populate(globals())

from ..ndarray import register as _nd_register  # noqa: E402


def zeros(shape, dtype=None, **kwargs):
    from . import _functions

    return _functions["_zeros"](shape=shape, dtype=dtype, **kwargs)


def ones(shape, dtype=None, **kwargs):
    from . import _functions

    return _functions["_ones"](shape=shape, dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, name=None):
    from . import _functions

    return _functions["_arange"](start=start, stop=stop, step=step,
                                 repeat=repeat, dtype=dtype, name=name)
