"""Symbol op-function codegen + arg-shape rules.

Reference mechanism: python/mxnet/symbol/register.py (same codegen as
ndarray — one function per registered op, composing graph nodes instead of
executing). Auto-creation of weight/bias variables when omitted matches the
reference's nnvm composition behavior (sym.Convolution(data=d, ...) creates
convN_weight/convN_bias vars), driven by the per-op input-slot tables below.
"""
from __future__ import annotations

import inspect

from .. import ops as _ops
from ..base import MXNetError
from .symbol import Symbol, _Node, var

# op -> ordered array-input slot names; entries after `|` are aux states
# (BatchNorm moving stats — hidden-output write-back targets).
_INPUT_SLOTS = {
    "FullyConnected": (["data", "weight", "bias"], []),
    "Convolution": (["data", "weight", "bias"], []),
    "Deconvolution": (["data", "weight", "bias"], []),
    "BatchNorm": (["data", "gamma", "beta"], ["moving_mean", "moving_var"]),
    "BatchNormRelu": (["data", "gamma", "beta"],
                      ["moving_mean", "moving_var"]),
    "BatchNormAddRelu": (["data", "addend", "gamma", "beta"],
                         ["moving_mean", "moving_var"]),
    "LayerNorm": (["data", "gamma", "beta"], []),
    "InstanceNorm": (["data", "gamma", "beta"], []),
    "Embedding": (["data", "weight"], []),
    "LeakyReLU": (["data", "gamma"], []),
    "RNN": (["data", "parameters", "state", "state_cell"], []),
    "SoftmaxOutput": (["data", "label"], []),
    "LinearRegressionOutput": (["data", "label"], []),
    "LogisticRegressionOutput": (["data", "label"], []),
    "MAERegressionOutput": (["data", "label"], []),
    # quantized compute ops (quantize_graph output): weight/bias vars sit
    # behind _contrib_quantize_v2 nodes; min/max slots carry no var shapes
    "_contrib_quantized_conv": (
        ["data", "weight", "bias", "min_data", "max_data", "min_weight",
         "max_weight"], []),
    "_contrib_quantized_fully_connected": (
        ["data", "weight", "bias", "min_data", "max_data", "min_weight",
         "max_weight"], []),
}

# single-input ops whose output shape equals the first input's shape AND
# that sit between a weight var and its consuming rule-op in real graphs
# (quantized graphs put _contrib_quantize_v2 between var and conv/fc);
# shape assignment walks through them to reach the var
_SHAPE_TRANSPARENT = {
    "_contrib_quantize_v2", "quantize_v2", "_contrib_quantize", "quantize",
    "Cast", "cast", "BlockGrad", "identity", "_copy",
}

# ops whose optional trailing array inputs are dropped by a flag
_OPTIONAL_DROP = {
    "FullyConnected": ("no_bias", ["bias"]),
    "Convolution": ("no_bias", ["bias"]),
    "Deconvolution": ("no_bias", ["bias"]),
}


def _slot_names(opname, attrs):
    entry = _INPUT_SLOTS.get(opname)
    if entry is None:
        return None, ()
    slots, aux = entry
    drop = _OPTIONAL_DROP.get(opname)
    if drop is not None:
        flag, names = drop
        if attrs.get(flag):
            slots = [s for s in slots if s not in names]
    if opname == "LeakyReLU" and attrs.get("act_type", "leaky") != "prelu":
        slots = ["data"]
    if opname == "RNN":
        if str(attrs.get("mode", "lstm")) != "lstm":
            slots = [s for s in slots if s != "state_cell"]
    return list(slots), tuple(aux)


def _make_symbol_function(opdef):
    fn = opdef.fn
    try:
        sig = inspect.signature(fn)
        params = list(sig.parameters.values())
    except (TypeError, ValueError):
        params = []
    if opdef.needs_rng and params and params[0].name == "rng":
        params = params[1:]
    var_pos = any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params)
    pos_names = [p.name for p in params
                 if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                               inspect.Parameter.POSITIONAL_OR_KEYWORD)]

    def generated(*args, name=None, attr=None, **kwargs):
        inputs = []          # [(slot_name_or_None, Symbol)]
        attrs = {}
        if var_pos:
            for a in args:
                if not isinstance(a, Symbol):
                    raise TypeError("%s: positional args must be Symbol" % opdef.name)
                inputs.append((None, a))
            kwargs.pop("num_args", None)
            for k, v in kwargs.items():
                if isinstance(v, Symbol):
                    inputs.append((k, v))
                else:
                    attrs[k] = v
        else:
            consumed = set()
            for i, a in enumerate(args):
                pname = pos_names[i] if i < len(pos_names) else None
                if isinstance(a, Symbol):
                    inputs.append((pname, a))
                    consumed.add(pname)
                elif pname is not None:
                    attrs[pname] = a
                    consumed.add(pname)
            for pname in pos_names:
                if pname in consumed or pname not in kwargs:
                    continue
                if isinstance(kwargs[pname], Symbol):
                    inputs.append((pname, kwargs.pop(pname)))
            attrs.update({k: v for k, v in kwargs.items()
                          if not isinstance(v, Symbol)})
        attrs = {k: v for k, v in attrs.items() if v is not None}
        attrs.pop("is_train", None)

        from .. import name as _name_mod

        node_name = _name_mod.current().get(name, opdef.name.lstrip("_").lower())
        slots, aux_names = _slot_names(opdef.name, attrs)
        if slots is None:
            # no table entry: inputs are whatever Symbols were passed.
            # When they bind to non-leading parameters (an optional array
            # slot was skipped — e.g. CTCLoss label_lengths without
            # pred_lengths), record the parameter names as graph metadata
            # (dunder attrs are filtered from op params at eval) so
            # execution binds by keyword instead of silently shifting
            # later arrays into the wrong slot
            if inputs and all(nm is not None for nm, _ in inputs):
                pn_order = [nm for nm, _ in inputs]
                if pn_order != pos_names[:len(pn_order)]:
                    attrs["__input_names__"] = tuple(pn_order)
            edges = [s._outputs[0] for _, s in inputs]
            aux_slots = ()
            n_hidden = (opdef.num_outputs - opdef.visible_outputs
                        if opdef.num_outputs > 0 else 0)
            if n_hidden > 0:
                aux_slots = tuple(range(len(edges) - n_hidden, len(edges)))
        else:
            by_slot = {}
            unnamed = [s for nm, s in inputs if nm is None]
            for nm, s in inputs:
                if nm is not None:
                    by_slot[nm] = s
            edges = []
            full = slots + list(aux_names)
            for slot in full:
                if slot in by_slot:
                    edges.append(by_slot[slot]._outputs[0])
                elif unnamed:
                    edges.append(unnamed.pop(0)._outputs[0])
                else:
                    # auto-create the variable (reference nnvm behavior)
                    edges.append(var("%s_%s" % (node_name, slot))._outputs[0])
            aux_slots = tuple(range(len(slots), len(full)))
        from .. import attribute as _attribute
        from .symbol import _wrap_attr_keys

        attr = _attribute.current().get(attr)
        if attr:
            attrs = dict(attrs, **_wrap_attr_keys(attr))
        node = _Node(opdef.name, node_name, attrs, edges, aux_slots)
        if opdef.num_outputs > 0:
            nvis = opdef.visible_outputs
        elif opdef.num_outputs_fn is not None:
            nvis = opdef.num_outputs_fn(attrs)
        else:
            nvis = 1
        return Symbol([(node, i) for i in range(max(1, nvis))])

    generated.__name__ = opdef.name
    # `params` already has the internal rng arg stripped (the key is
    # injected at execution); show the caller-facing signature
    sig_str = "(%s)" % ", ".join(
        [str(p) for p in params] + ["name=None", "attr=None"]) \
        if params else "(...)"
    generated.__doc__ = "%s%s\n\n%s\n(symbol function auto-generated " \
        "from op '%s')" % (opdef.name, sig_str,
                           (opdef.fn.__doc__ or "").strip(), opdef.name)
    return generated


class _OpNamespace(object):
    pass


def populate(target_module_dict):
    contrib = _OpNamespace()
    linalg = _OpNamespace()
    random_ns = _OpNamespace()
    sparse_ns = _OpNamespace()
    image_ns = _OpNamespace()
    op_ns = _OpNamespace()
    functions = {}
    for name in _ops.list_ops():
        opdef = _ops.get(name)
        f = _make_symbol_function(opdef)
        functions[name] = f
        if name.startswith("_contrib_"):
            setattr(contrib, name[len("_contrib_"):], f)
        elif name.startswith("_linalg_"):
            setattr(linalg, name[len("_linalg_"):], f)
        elif name.startswith("_random_"):
            setattr(random_ns, name[len("_random_"):], f)
        elif name.startswith("_sample_"):
            setattr(random_ns, name[1:], f)
        elif name.startswith("_image_"):
            setattr(image_ns, name[len("_image_"):], f)
        if name.isidentifier():
            setattr(op_ns, name, f)  # flat mx.sym.op.* (reference op.py)
        if not name.startswith("_contrib_") and not name.startswith("_linalg_"):
            target_module_dict.setdefault(name, f)
    target_module_dict["contrib"] = contrib
    target_module_dict["linalg"] = linalg
    target_module_dict["random"] = random_ns
    target_module_dict["sparse"] = sparse_ns
    target_module_dict.setdefault("image", image_ns)
    target_module_dict.setdefault("op", op_ns)
    return functions


# --------------------------------------------------------------------------
# arg-shape rules: fill unknown variable shapes from op attrs + data shape
# (the forward half of the reference's bidirectional InferShape pass,
# src/executor/infer_graph_attr_pass.cc — enough for simple_bind flows)
# --------------------------------------------------------------------------

def _prod(xs):
    r = 1
    for x in xs:
        r *= x
    return r


def _fc_rule(attrs, in_shapes):
    data = in_shapes[0]
    nh = int(attrs.get("num_hidden", 0))
    flat = attrs.get("flatten", True)
    in_dim = _prod(data[1:]) if flat else data[-1]
    return {"weight": (nh, in_dim), "bias": (nh,)}


def _conv_rule(attrs, in_shapes):
    data = in_shapes[0]
    nf = int(attrs.get("num_filter", 0))
    kernel = tuple(attrs.get("kernel", ()))
    ng = int(attrs.get("num_group", 1))
    return {"weight": (nf, data[1] // ng) + kernel, "bias": (nf,)}


def _deconv_rule(attrs, in_shapes):
    data = in_shapes[0]
    nf = int(attrs.get("num_filter", 0))
    kernel = tuple(attrs.get("kernel", ()))
    ng = int(attrs.get("num_group", 1))
    return {"weight": (data[1], nf // ng) + kernel, "bias": (nf,)}


def _bn_rule(attrs, in_shapes):
    ax = int(attrs.get("axis", 1)) % len(in_shapes[0])
    c = in_shapes[0][ax]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,), "moving_var": (c,)}


def _ln_rule(attrs, in_shapes):
    ax = int(attrs.get("axis", -1)) % len(in_shapes[0])
    c = in_shapes[0][ax]
    return {"gamma": (c,), "beta": (c,)}


def _embed_rule(attrs, in_shapes):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _prelu_rule(attrs, in_shapes):
    if attrs.get("act_type") == "prelu":
        return {"gamma": (in_shapes[0][1],)}
    return {}


def _rnn_rule(attrs, in_shapes):
    # data [T, N, C]; parameters = flat fused buffer (ops/rnn.py layout)
    from ..ops.rnn import rnn_param_size

    data = in_shapes[0]
    sh = int(attrs["state_size"])
    nl = int(attrs.get("num_layers", 1))
    bi = bool(attrs.get("bidirectional", False))
    mode = str(attrs.get("mode", "lstm"))
    d = 2 if bi else 1
    n_states = 2 if mode == "lstm" else 1
    out = {"parameters": (rnn_param_size(nl, data[2], sh, bi, mode),),
           "state": (nl * d, data[1], sh)}
    if n_states == 2:
        out["state_cell"] = (nl * d, data[1], sh)
    return out


_ARG_SHAPE_RULES = {
    "FullyConnected": _fc_rule,
    "Convolution": _conv_rule,
    "Deconvolution": _deconv_rule,
    "BatchNorm": _bn_rule,
    "BatchNormRelu": _bn_rule,
    "BatchNormAddRelu": _bn_rule,
    "LayerNorm": _ln_rule,
    "InstanceNorm": _ln_rule,
    "Embedding": _embed_rule,
    "LeakyReLU": _prelu_rule,
    "RNN": _rnn_rule,
    # quantized kernels keep the fp32 op's weight geometry (the int8 conv
    # consumes the same OIHW weight the fp32 conv would)
    "_contrib_quantized_conv": _conv_rule,
    "_contrib_quantized_fully_connected": _fc_rule,
}


def infer_var_shapes(sym, known):
    """Walk the graph forward, filling variable shapes: known data shapes
    propagate through jax.eval_shape; parameter vars attached to table ops
    get their shapes from the op's attr rule."""
    import jax
    import jax.numpy as jnp

    from ..ndarray.ndarray import _takes_is_train

    shapes = dict(known)
    out_shapes = {}   # id(node) -> tuple of output shapes

    def resolve_var(src):
        """Walk through shape-preserving ops (quantize/cast/...) to the
        underlying variable, so rule shapes land on the var even when the
        graph interposes a quantize node (quantize_graph output)."""
        seen = 0
        while not src.is_var and src.op in _SHAPE_TRANSPARENT \
                and src.inputs and seen < 16:
            src = src.inputs[0][0]
            seen += 1
        return src if src.is_var else None

    # iterate to fixpoint: a rule visit can assign a var whose consuming
    # quantize/cast node topologically precedes the rule op — the next
    # pass then forward-evals that node (at most a few passes in practice)
    topo = list(sym._topo())
    for _pass in range(max(2, len(topo))):
        progressed = False
        for node in topo:
            if node.is_var:
                if node.name not in shapes and node._shape is not None and \
                        not any(s == 0 for s in node._shape):
                    shapes[node.name] = tuple(node._shape)
                if node.name in shapes and id(node) not in out_shapes:
                    out_shapes[id(node)] = (shapes[node.name],)
                    progressed = True
                continue
            rule = _ARG_SHAPE_RULES.get(node.op)
            if rule is not None:
                first_src, first_idx = node.inputs[0]
                if id(first_src) in out_shapes:
                    data_shape = out_shapes[id(first_src)][first_idx]
                    try:
                        slot_shapes = rule(node.attrs, [data_shape])
                    except (KeyError, MXNetError):
                        slot_shapes = {}
                    slots, aux = _slot_names(node.op, node.attrs)
                    full = (slots or []) + list(aux)
                    for slot, (src, _) in zip(full, node.inputs):
                        if slot not in slot_shapes:
                            continue
                        var = src if src.is_var else resolve_var(src)
                        if var is not None and var.name not in shapes:
                            shapes[var.name] = tuple(slot_shapes[slot])
                            out_shapes[id(var)] = (shapes[var.name],)
                            progressed = True
            if id(node) in out_shapes:
                continue
            # forward eval if every input known
            ready = all(id(src) in out_shapes and
                        len(out_shapes[id(src)]) > idx
                        for src, idx in node.inputs)
            if not ready:
                continue
            opdef = _ops.get(node.op)
            # dunder attrs are graph metadata (user __key__ attrs,
            # __input_names__ slot binding), not op params
            attrs = {k: v for k, v in node.attrs.items()
                     if not (k.startswith("__") and k.endswith("__"))}
            if _takes_is_train(opdef):
                attrs.setdefault("is_train", True)
            bind_names = node.attrs.get("__input_names__")
            in_structs = [jax.ShapeDtypeStruct(out_shapes[id(src)][idx],
                                               jnp.float32)
                          for src, idx in node.inputs]
            if bind_names is not None and len(bind_names) == len(in_structs):
                def _call(*a, _bn=tuple(bind_names), _at=attrs, _f=opdef.fn):
                    if opdef.needs_rng:
                        return _f(a[0], **dict(zip(_bn, a[1:])), **_at)
                    return _f(**dict(zip(_bn, a)), **_at)
            else:
                def _call(*a, _at=attrs, _f=opdef.fn):
                    return _f(*a, **_at)
            if opdef.needs_rng:
                in_structs = [jax.ShapeDtypeStruct((2,), jnp.uint32)] \
                    + in_structs

            try:
                res = jax.eval_shape(_call, *in_structs)
            except Exception:
                continue
            res = tuple(res) if isinstance(res, (tuple, list)) else (res,)
            out_shapes[id(node)] = tuple(tuple(r.shape) for r in res)
            progressed = True
        if not progressed:
            break
    return shapes
