"""Profiler: chrome://tracing output + aggregate stats.

TPU-native equivalent of the reference's profiler (src/profiler/profiler.h:87
emitting chrome-trace JSON; Python front python/mxnet/profiler.py —
set_config/set_state/dump, scoped Domain/Task/Frame/Event/Counter/Marker;
the engine wraps every op in a ProfileOperator when profiling is on,
graph_executor.cc:1309). Here the op hook lives in `ndarray.invoke` /
`Executor.forward` dispatch; XLA kernel-level traces come from wrapping
`jax.profiler` (xplane) via `start_xla_trace/stop_xla_trace`.

Op timing semantics: dispatch is async (XLA enqueues); by default the
recorded duration is dispatch time. Set `profile_sync=True` in set_config
(or env MXTPU_PROFILE_SYNC=1) to block per op and record true device time —
the analogue of the reference's engine-side start/end stamps.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Domain", "Task", "Frame", "Event", "Counter", "Marker",
           "start_xla_trace", "stop_xla_trace"]

_lock = threading.Lock()
_events = []            # chrome trace event dicts
_aggregate = {}         # name -> [count, total_us, min_us, max_us]
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "profile_sync": os.environ.get("MXTPU_PROFILE_SYNC", "") not in ("", "0"),
}
_state = {"running": False, "paused": False}
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def is_active():
    return _state["running"] and not _state["paused"]


def profile_sync():
    return _config["profile_sync"]


def set_config(**kwargs):
    """Configure (reference: profiler.py set_config — filename, profile_all,
    profile_symbolic/imperative/memory/api, aggregate_stats)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError("unknown profiler config keys: %s" % sorted(unknown))
    _config.update(kwargs)


def set_state(state_="stop"):
    """'run' | 'stop' (reference: profiler.py set_state)."""
    if state_ not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    _state["running"] = state_ == "run"
    _state["paused"] = False


def state():
    return "run" if _state["running"] else "stop"


def pause():
    _state["paused"] = True


def resume():
    _state["paused"] = False


def _emit(name, cat, start_us, dur_us, args=None):
    ev = {"name": name, "cat": cat, "ph": "X", "ts": start_us, "dur": dur_us,
          "pid": 0, "tid": threading.get_ident() % 10000}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        if _config["aggregate_stats"]:
            st = _aggregate.setdefault(name, [0, 0.0, float("inf"), 0.0])
            st[0] += 1
            st[1] += dur_us
            st[2] = min(st[2], dur_us)
            st[3] = max(st[3], dur_us)


def _category_enabled(cat):
    if _config["profile_all"]:
        return True
    if cat == "imperative":
        return _config["profile_imperative"]
    if cat == "symbolic":
        return _config["profile_symbolic"]
    if cat == "api":
        return _config["profile_api"]
    return True


def record_op(name, start_us, dur_us, cat="imperative"):
    """Called from the dispatch layer around each op (the ProfileOperator
    hook, reference profiler.h:1085). `cat` is the reference's
    profile_imperative / profile_symbolic config split."""
    if _category_enabled(cat):
        _emit(name, cat, start_us, dur_us)


def _block_results(results):
    if isinstance(results, (tuple, list)):
        for r in results:
            _block_results(r)
    elif hasattr(results, "block_until_ready"):
        results.block_until_ready()


def timed_call(name, fn, args, cat="imperative"):
    """Run fn(*args), recording it as one op event when profiling is active
    (single shared wrapper for every dispatch site)."""
    if not is_active() or not _category_enabled(cat):
        return fn(*args)
    t0 = _now_us()
    results = fn(*args)
    if profile_sync():
        _block_results(results)
    record_op(name, t0, _now_us() - t0, cat=cat)
    return results


def record_memory(name, nbytes):
    if _config["profile_memory"] or _config["profile_all"]:
        with _lock:
            _events.append({"name": "memory", "ph": "C", "ts": _now_us(),
                            "pid": 0, "args": {name: nbytes}})


def dump(finished=True, profile_process="worker"):
    """Write the chrome trace file (reference: profiler.py dump ->
    MXDumpProfile). Open it at chrome://tracing or perfetto.dev."""
    with _lock:
        data = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(data, f)
    if finished:
        with _lock:
            _events.clear()


def dumps(reset=False):
    """Aggregate summary table string (reference: profiler.py dumps ->
    MXAggregateProfileStatsPrint)."""
    with _lock:
        rows = sorted(_aggregate.items(), key=lambda kv: -kv[1][1])
        out = ["%-40s %10s %14s %14s %14s %14s" %
               ("Name", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)", "Max(ms)")]
        for name, (cnt, tot, mn, mx) in rows:
            out.append("%-40s %10d %14.3f %14.3f %14.3f %14.3f" %
                       (name, cnt, tot / 1e3, tot / cnt / 1e3, mn / 1e3, mx / 1e3))
        if reset:
            _aggregate.clear()
    return "\n".join(out)


# --------------------------------------------------------------------------
# scoped objects (reference: profiler.py Domain/Task/Frame/Event/Counter/Marker)
# --------------------------------------------------------------------------

class Domain:
    """Grouping namespace (reference: profiler.py Domain)."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_counter(self, name, value=None):
        return Counter(name, self, value)

    def new_marker(self, name):
        return Marker(name, self)


class _Scoped:
    _cat = "task"

    def __init__(self, name, domain=None):
        self.name = name
        self.domain = domain
        self._start = None

    def start(self):
        self._start = _now_us()
        return self

    def stop(self):
        if self._start is None:
            return
        if is_active():
            nm = self.name if self.domain is None else \
                "%s::%s" % (self.domain.name, self.name)
            _emit(nm, self._cat, self._start, _now_us() - self._start)
        self._start = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Scoped):
    _cat = "task"


class Frame(_Scoped):
    _cat = "frame"


class Event(_Scoped):
    _cat = "event"


class Counter:
    """Numeric counter series (reference: profiler.py Counter)."""

    def __init__(self, name, domain=None, value=None):
        self.name = name
        self.domain = domain
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_active():
            with _lock:
                _events.append({"name": self.name, "ph": "C", "ts": _now_us(),
                                "pid": 0, "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant event (reference: profiler.py Marker)."""

    def __init__(self, name, domain=None):
        self.name = name
        self.domain = domain

    def mark(self, scope="process"):
        if is_active():
            with _lock:
                _events.append({"name": self.name, "ph": "i", "ts": _now_us(),
                                "pid": 0, "s": {"process": "p", "thread": "t",
                                                "global": "g"}.get(scope, "p")})


# --------------------------------------------------------------------------
# XLA-level tracing (xplane) — the TPU analogue of nvprof/VTune hooks
# --------------------------------------------------------------------------

_xla_trace_dir = [None]


def start_xla_trace(log_dir="/tmp/mxtpu_xla_trace"):
    import jax

    jax.profiler.start_trace(log_dir)
    _xla_trace_dir[0] = log_dir
    return log_dir


def stop_xla_trace():
    import jax

    jax.profiler.stop_trace()
    d, _xla_trace_dir[0] = _xla_trace_dir[0], None
    return d
