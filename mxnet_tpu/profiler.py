"""Profiler: chrome://tracing output + aggregate stats.

TPU-native equivalent of the reference's profiler (src/profiler/profiler.h:87
emitting chrome-trace JSON; Python front python/mxnet/profiler.py —
set_config/set_state/dump, scoped Domain/Task/Frame/Event/Counter/Marker;
the engine wraps every op in a ProfileOperator when profiling is on,
graph_executor.cc:1309). Here the op hook lives in `ndarray.invoke` /
`Executor.forward` dispatch; XLA kernel-level traces come from wrapping
`jax.profiler` (xplane) via `start_xla_trace/stop_xla_trace`.

Op timing semantics: dispatch is async (XLA enqueues); by default the
recorded duration is dispatch time. Set `profile_sync=True` in set_config
(or env MXTPU_PROFILE_SYNC=1) to block per op and record true device time —
the analogue of the reference's engine-side start/end stamps.
"""
from __future__ import annotations

import json
import threading
import time

from . import env as _env
from .base import MXNetError
from .telemetry import core as _telemetry

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Domain", "Task", "Frame", "Event", "Counter", "Marker",
           "start_xla_trace", "stop_xla_trace"]

_lock = threading.Lock()
_events = []            # chrome trace event dicts
_aggregate = {}         # name -> [count, total_us, min_us, max_us]
_tids = {}              # thread ident -> (stable small tid, registered name)
_rank_cache = [None]    # launcher rank, resolved once (stamps trace pids)
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "profile_sync": _env.get("MXTPU_PROFILE_SYNC"),
}
_state = {"running": False, "paused": False}
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def is_active():
    return _state["running"] and not _state["paused"]


def profile_sync():
    return _config["profile_sync"]


def set_config(**kwargs):
    """Configure (reference: profiler.py set_config — filename, profile_all,
    profile_symbolic/imperative/memory/api, aggregate_stats)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError("unknown profiler config keys: %s" % sorted(unknown))
    _config.update(kwargs)


def set_state(state_="stop"):
    """'run' | 'stop' (reference: profiler.py set_state)."""
    if state_ not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop'")
    _state["running"] = state_ == "run"
    _state["paused"] = False


def state():
    return "run" if _state["running"] else "stop"


def pause():
    _state["paused"] = True


def resume():
    _state["paused"] = False


def _rank():
    """Trace pid = launcher rank, so merged multi-rank traces show one
    process lane per rank (tools/trace_merge.py)."""
    if _rank_cache[0] is None:
        _rank_cache[0] = _telemetry.rank()
    return _rank_cache[0]


def _tid():
    """Stable per-thread small id. The old `get_ident() % 10000` was
    collision-prone (idents are pthread addresses; two threads 10000*k
    apart collapsed into one trace lane). First use of a thread also emits
    its chrome-trace `thread_name` metadata event so merged traces show
    named lanes."""
    ident = threading.get_ident()
    entry = _tids.get(ident)
    if entry is None:
        with _lock:
            entry = _tids.get(ident)
            if entry is None:
                name = threading.current_thread().name
                entry = (len(_tids) + 1, name)
                _tids[ident] = entry
                _events.append({"ph": "M", "name": "thread_name",
                                "pid": _rank(), "tid": entry[0],
                                "args": {"name": name}})
    return entry[0]


def _emit(name, cat, start_us, dur_us, args=None):
    ev = {"name": name, "cat": cat, "ph": "X", "ts": start_us, "dur": dur_us,
          "pid": _rank(), "tid": _tid()}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        if _config["aggregate_stats"]:
            st = _aggregate.setdefault(name, [0, 0.0, float("inf"), 0.0])
            st[0] += 1
            st[1] += dur_us
            st[2] = min(st[2], dur_us)
            st[3] = max(st[3], dur_us)


def _category_enabled(cat):
    if _config["profile_all"]:
        return True
    if cat == "imperative":
        return _config["profile_imperative"]
    if cat == "symbolic":
        return _config["profile_symbolic"]
    if cat == "api":
        return _config["profile_api"]
    return True


def record_op(name, start_us, dur_us, cat="imperative"):
    """Called from the dispatch layer around each op (the ProfileOperator
    hook, reference profiler.h:1085). `cat` is the reference's
    profile_imperative / profile_symbolic config split."""
    if _category_enabled(cat):
        _emit(name, cat, start_us, dur_us)


def _block_results(results):
    if isinstance(results, (tuple, list)):
        for r in results:
            _block_results(r)
    elif hasattr(results, "block_until_ready"):
        results.block_until_ready()


_DISPATCH_COUNTERS = {}


def _dispatch_counter(cat):
    c = _DISPATCH_COUNTERS.get(cat)
    if c is None:
        if not _telemetry._STATE.enabled:
            return _telemetry._NULL  # don't cache the null across a toggle
        c = _telemetry.counter("mxtpu_op_dispatch_total", {"cat": cat})
        _DISPATCH_COUNTERS[cat] = c
    return c


def timed_call(name, fn, args, cat="imperative"):
    """Run fn(*args), recording it as one op event when profiling is active
    (single shared wrapper for every dispatch site). Always counts the
    dispatch in telemetry (`mxtpu_op_dispatch_total{cat}`) — the always-on
    layer rides the same choke point the profiler hook uses."""
    _dispatch_counter(cat).inc()
    if not is_active() or not _category_enabled(cat):
        return fn(*args)
    t0 = _now_us()
    results = fn(*args)
    if profile_sync():
        _block_results(results)
    record_op(name, t0, _now_us() - t0, cat=cat)
    return results


def record_memory(name, nbytes):
    if _config["profile_memory"] or _config["profile_all"]:
        pid = _rank()
        with _lock:
            _events.append({"name": "memory", "ph": "C", "ts": _now_us(),
                            "pid": pid, "args": {name: nbytes}})


def dump(finished=True, profile_process="worker"):
    """Write the chrome trace file (reference: profiler.py dump ->
    MXDumpProfile). Open it at chrome://tracing or perfetto.dev; merge
    per-rank dumps with tools/trace_merge.py (each dump stamps pid=rank and
    carries process_name/thread_name metadata so the merged timeline shows
    named rank/thread lanes).

    `finished=True` (default) also RESETS the aggregate-stats table, not
    just the event list — back-to-back profile sessions must not mix rows
    (the reference's dump-finished semantics)."""
    r = _rank()
    meta = [
        {"ph": "M", "name": "process_name", "pid": r, "tid": 0,
         "args": {"name": "rank %d (%s)" % (r, profile_process)}},
        {"ph": "M", "name": "process_sort_index", "pid": r, "tid": 0,
         "args": {"sort_index": r}},
    ]
    with _lock:
        data = {"traceEvents": meta + list(_events), "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(data, f)
    if finished:
        with _lock:
            _events.clear()
            _aggregate.clear()
            # next session re-registers threads (their thread_name metadata
            # events were just cleared with the event list)
            _tids.clear()


def dumps(reset=False):
    """Aggregate summary table string (reference: profiler.py dumps ->
    MXAggregateProfileStatsPrint)."""
    with _lock:
        rows = sorted(_aggregate.items(), key=lambda kv: -kv[1][1])
        out = ["%-40s %10s %14s %14s %14s %14s" %
               ("Name", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)", "Max(ms)")]
        for name, (cnt, tot, mn, mx) in rows:
            out.append("%-40s %10d %14.3f %14.3f %14.3f %14.3f" %
                       (name, cnt, tot / 1e3, tot / cnt / 1e3, mn / 1e3, mx / 1e3))
        if reset:
            _aggregate.clear()
    return "\n".join(out)


# --------------------------------------------------------------------------
# scoped objects (reference: profiler.py Domain/Task/Frame/Event/Counter/Marker)
# --------------------------------------------------------------------------

class Domain:
    """Grouping namespace (reference: profiler.py Domain)."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_counter(self, name, value=None):
        return Counter(name, self, value)

    def new_marker(self, name):
        return Marker(name, self)


class _Scoped:
    _cat = "task"

    def __init__(self, name, domain=None):
        self.name = name
        self.domain = domain
        self._start = None

    def start(self):
        self._start = _now_us()
        return self

    def stop(self):
        if self._start is None:
            return
        if is_active():
            nm = self.name if self.domain is None else \
                "%s::%s" % (self.domain.name, self.name)
            _emit(nm, self._cat, self._start, _now_us() - self._start)
        self._start = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Scoped):
    _cat = "task"


class Frame(_Scoped):
    _cat = "frame"


class Event(_Scoped):
    _cat = "event"


class Counter:
    """Numeric counter series (reference: profiler.py Counter)."""

    def __init__(self, name, domain=None, value=None):
        self.name = name
        self.domain = domain
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if is_active():
            pid = _rank()
            with _lock:
                _events.append({"name": self.name, "ph": "C", "ts": _now_us(),
                                "pid": pid, "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant event (reference: profiler.py Marker)."""

    def __init__(self, name, domain=None):
        self.name = name
        self.domain = domain

    def mark(self, scope="process"):
        if is_active():
            pid, tid = _rank(), _tid()
            with _lock:
                _events.append({"name": self.name, "ph": "i", "ts": _now_us(),
                                "pid": pid, "tid": tid,
                                "s": {"process": "p", "thread": "t",
                                      "global": "g"}.get(scope, "p")})


# --------------------------------------------------------------------------
# XLA-level tracing (xplane) — the TPU analogue of nvprof/VTune hooks
# --------------------------------------------------------------------------

_xla_trace_dir = [None]


def start_xla_trace(log_dir="/tmp/mxtpu_xla_trace"):
    import jax

    jax.profiler.start_trace(log_dir)
    _xla_trace_dir[0] = log_dir
    return log_dir


def stop_xla_trace():
    import jax

    jax.profiler.stop_trace()
    d, _xla_trace_dir[0] = _xla_trace_dir[0], None
    return d
