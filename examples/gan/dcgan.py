"""DCGAN: adversarial training end to end.

Reference analogue: example/gan/dcgan.py (deconv generator vs conv
discriminator, alternating updates). Scaled to 16x16 synthetic data so it
runs anywhere; exercises Deconvolution, BatchNorm under dual optimizers,
and detached-generator updates — the graph patterns GANs stress.

Run: JAX_PLATFORMS=cpu python examples/gan/dcgan.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

Z = 16


def build_generator():
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # z (B, Z, 1, 1) -> (B, 1, 16, 16)
        net.add(nn.Conv2DTranspose(32, 4, strides=1, padding=0,
                                   use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.Activation("tanh"))
    return net


def build_discriminator():
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(16, 4, strides=2, padding=1),
                nn.LeakyReLU(0.2),
                nn.Conv2D(32, 4, strides=2, padding=1),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(1, 4, strides=1, padding=0))
    return net


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()
    rng = np.random.RandomState(0)
    # "real" data: smooth blobs in [-1, 1]
    yy, xx = np.mgrid[0:16, 0:16].astype(np.float32)

    def real_batch(n):
        cx = rng.uniform(4, 12, (n, 1, 1))
        cy = rng.uniform(4, 12, (n, 1, 1))
        img = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / 8.0)
        return (img * 2 - 1).astype(np.float32)[:, None]

    gen, disc = build_generator(), build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": 2e-3, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": 2e-3, "beta1": 0.5})
    lossfn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    B = 16
    for step in range(args.steps):
        real = mx.nd.array(real_batch(B))
        z = mx.nd.array(rng.randn(B, Z, 1, 1).astype(np.float32))
        ones = mx.nd.ones((B,))
        zeros = mx.nd.zeros((B,))

        # discriminator: real -> 1, fake (detached generator) -> 0
        with autograd.record():
            fake = gen(z)
            d_loss = (lossfn(disc(real).reshape((B,)), ones) +
                      lossfn(disc(fake.detach()).reshape((B,)), zeros)).mean()
        d_loss.backward()
        d_tr.step(B)

        # generator: fool the discriminator
        with autograd.record():
            g_loss = lossfn(disc(gen(z)).reshape((B,)), ones).mean()
        g_loss.backward()
        g_tr.step(B)

        if step % 10 == 0 or step == 39:
            print("step %2d  d_loss %.4f  g_loss %.4f"
                  % (step, float(d_loss.asnumpy()),
                     float(g_loss.asnumpy())))

    assert np.isfinite(float(d_loss.asnumpy()))
    assert np.isfinite(float(g_loss.asnumpy()))
    fake_np = fake.asnumpy()
    assert fake_np.shape == (B, 1, 16, 16)
    print("done — generator output range [%.2f, %.2f]"
          % (fake_np.min(), fake_np.max()))


if __name__ == "__main__":
    main()
