#!/usr/bin/env python
"""ImageNet training — the reference's headline script
(example/image-classification/train_imagenet.py + common/fit.py), with the
same argument surface (subset) over the gluon model zoo.

Data: point --data-train/--data-val at RecordIO files (ImageRecordIter,
same .rec format as the reference, packed by tools/im2rec.py); without
them the script runs on synthetic ImageNet-shaped batches so it is
runnable anywhere (zero-egress CI, perf smoke on the chip).

TPU-first knobs beyond the reference: --dtype bfloat16 (bf16 compute +
fp32 master weights via DistributedTrainer) and --layout NHWC
(channels-last zoo build, the MXU-preferred layout).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np


def add_fit_args(parser):
    """reference: common/fit.py:77 add_fit_args (subset)."""
    t = parser.add_argument_group("Training")
    t.add_argument("--network", type=str, default="resnet50_v1",
                   help="model zoo factory name (resnet50_v1, resnet18_v1, "
                        "inception_v3, mobilenet1_0, ...)")
    t.add_argument("--kv-store", type=str, default="device")
    t.add_argument("--num-epochs", type=int, default=1)
    t.add_argument("--lr", type=float, default=0.1)
    t.add_argument("--lr-factor", type=float, default=0.1)
    t.add_argument("--lr-step-epochs", type=str, default="30,60")
    t.add_argument("--optimizer", type=str, default="sgd")
    t.add_argument("--mom", type=float, default=0.9)
    t.add_argument("--wd", type=float, default=1e-4)
    t.add_argument("--batch-size", type=int, default=32)
    t.add_argument("--disp-batches", type=int, default=20)
    t.add_argument("--model-prefix", type=str, default=None)
    t.add_argument("--top-k", type=int, default=0)
    t.add_argument("--dtype", type=str, default="bfloat16",
                   choices=["float32", "bfloat16"])
    t.add_argument("--layout", type=str, default="NCHW",
                   choices=["NCHW", "NHWC"])
    t.add_argument("--num-classes", type=int, default=1000)
    t.add_argument("--image-shape", type=str, default="3,224,224")
    t.add_argument("--data-train", type=str, default=None,
                   help="RecordIO file (tools/im2rec.py); synthetic if unset")
    t.add_argument("--data-val", type=str, default=None)
    t.add_argument("--num-batches", type=int, default=10,
                   help="synthetic-data batches per epoch")
    return parser


def _synthetic_batches(args, shape, rng):
    for _ in range(args.num_batches):
        x = rng.uniform(-1, 1, (args.batch_size,) + shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, (args.batch_size,))
        yield x, y


def main():
    args = add_fit_args(argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)).parse_args()
    logging.basicConfig(level=logging.INFO)

    # a sitecustomize PJRT hook force-overrides jax_platforms at interpreter
    # start; re-assert the env's explicit choice so JAX_PLATFORMS=cpu runs
    # stay on CPU instead of dialing the accelerator tunnel
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    c, h, w = (int(s) for s in args.image_shape.split(","))
    nhwc = args.layout == "NHWC"
    shape = (h, w, c) if nhwc else (c, h, w)

    ctx = mx.tpu() if mx.context.num_gpus() else mx.cpu()
    fac = getattr(vision, args.network)
    with ctx:
        if nhwc:
            with gluon.nn.layout_scope():
                net = fac(classes=args.num_classes)
        else:
            net = fac(classes=args.num_classes)
        net.initialize(mx.init.Xavier(), ctx=ctx)
        net(mx.nd.zeros((args.batch_size,) + shape, ctx=ctx))

    import jax

    # data-parallel over every visible device (the reference script's
    # multi-GPU behavior); batch is sliced across the dp axis
    devices = jax.devices()
    dp = len(devices)
    while args.batch_size % dp:
        dp -= 1  # largest device count dividing the batch
    if dp != len(devices):
        logging.warning("using %d/%d devices (batch %d not divisible)",
                        dp, len(devices), args.batch_size)
    mesh = make_mesh([("dp", dp)], devices=devices[:dp])
    opt_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer == "sgd":
        opt_params["momentum"] = args.mom
    trainer = DistributedTrainer(
        net, args.optimizer, opt_params,
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype=None if args.dtype == "float32" else args.dtype)

    lr_steps = [int(e) for e in args.lr_step_epochs.split(",") if e]
    metric = mx.metric.Accuracy()
    if args.top_k:
        metric = mx.metric.CompositeEvalMetric(
            [metric, mx.metric.TopKAccuracy(args.top_k)])

    def _rec_batches(path, shuffle):
        it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(c, h, w),
                                   batch_size=args.batch_size,
                                   shuffle=shuffle)
        for b in it:
            xb = b.data[0]
            if nhwc:
                # device-side relayout; no host round trip
                xb = mx.nd.transpose(xb, (0, 2, 3, 1))
            yield xb, b.label[0], b.pad or 0

    def _evaluate(epoch):
        trainer.sync_params()  # copy mesh-trained values into the block
        metric.reset()
        for xb, yb, pad in _rec_batches(args.data_val, shuffle=False):
            with mx.autograd.predict_mode():
                out = net(xb.as_in_context(ctx))
            keep = xb.shape[0] - pad  # last batch pads by cycling samples;
            metric.update([yb[:keep].as_in_context(ctx)],  # don't score dups
                          [out[:keep]])
        for name, val in metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

    rng = np.random.RandomState(0)
    for epoch in range(args.num_epochs):
        if epoch in lr_steps:
            trainer.set_learning_rate(trainer.learning_rate * args.lr_factor)
        if args.data_train:
            batches = _rec_batches(args.data_train, shuffle=True)
        else:
            batches = ((mx.nd.array(x, ctx=ctx), mx.nd.array(y, ctx=ctx), 0)
                       for x, y in _synthetic_batches(args, shape, rng))

        tic = time.time()
        win_tic, win_n = time.time(), 0   # Speedometer-style window: the
        n = 0                             # first-batch compile cost only
        for i, (xb, yb, pad) in enumerate(batches):  # hits first interval
            # the padded tail still trains at the static batch shape
            # (reference behavior); only the sample accounting excludes it
            loss = trainer.step(xb.as_in_context(ctx),
                                yb.astype("float32").as_in_context(ctx))
            n += xb.shape[0] - pad
            win_n += xb.shape[0] - pad
            if (i + 1) % args.disp_batches == 0:
                logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                             "\tloss=%.4f", epoch, i + 1,
                             win_n / (time.time() - win_tic),
                             float(loss.asnumpy()))
                win_tic, win_n = time.time(), 0
        logging.info("Epoch[%d] Train-samples/sec=%f", epoch,
                     n / (time.time() - tic))
        logging.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)
        if args.data_val:
            _evaluate(epoch)

        if args.model_prefix:
            trainer.sync_params()  # export the trained weights, not init
            net.export(args.model_prefix, epoch=epoch)
    print("done: trained %s %s %s on %s" % (
        args.network, args.dtype, args.layout, ctx))


if __name__ == "__main__":
    main()
