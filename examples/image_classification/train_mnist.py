"""LeNet/MLP on MNIST via the symbolic Module API (reference:
example/image-classification/train_mnist.py — the BASELINE 'CPU smoke'
config). Reads idx-ubyte MNIST files from --data-dir if present, else
generates a separable synthetic set so the example runs in a zero-egress
environment.

    JAX_PLATFORMS=cpu python examples/image_classification/train_mnist.py
"""
import argparse
import os

import numpy as np


def lenet(num_classes=10):
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    h = mx.sym.Convolution(data=data, kernel=(5, 5), num_filter=20)
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    h = mx.sym.Convolution(h, kernel=(5, 5), num_filter=50)
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    h = mx.sym.Flatten(h)
    h = mx.sym.FullyConnected(h, num_hidden=500)
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def load_data(data_dir, n_synth=2048):
    import mxnet_tpu as mx

    try:
        train = mx.gluon.data.vision.MNIST(root=data_dir, train=True)
        X = train._data.astype(np.float32).transpose(0, 3, 1, 2) / 255.0
        y = np.asarray(train._label, np.float32)
    except Exception:
        rng = np.random.RandomState(0)
        y = rng.randint(0, 10, n_synth).astype(np.float32)
        X = rng.normal(0, 0.3, (n_synth, 1, 28, 28)).astype(np.float32)
        for i in range(n_synth):   # class-dependent bright square
            c = int(y[i])
            X[i, 0, 2 + 2 * (c // 5):6 + 2 * (c // 5),
              2 + 2 * (c % 5):6 + 2 * (c % 5)] += 2.0
    return X, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=os.path.join("~", ".mxnet",
                                                       "datasets", "mnist"))
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--optimizer", default="adam",
                    help="'sgd' + --lr 0.05 mirrors the reference defaults; "
                         "adam converges faster on the synthetic fallback set")
    ap.add_argument("--limit", type=int, default=0,
                    help="cap the dataset size (CI smoke configs)")
    args = ap.parse_args()

    import mxnet_tpu as mx

    X, y = load_data(args.data_dir)
    if args.limit:
        X, y = X[:args.limit], y[:args.limit]
    n_val = max(len(X) // 10, args.batch_size)
    train_iter = mx.io.NDArrayIter(X[n_val:], y[n_val:], args.batch_size,
                                   shuffle=True)
    val_iter = mx.io.NDArrayIter(X[:n_val], y[:n_val], args.batch_size)

    mod = mx.mod.Module(lenet(), context=mx.cpu())
    opt_params = {"learning_rate": args.lr}
    if args.optimizer == "sgd":
        opt_params["momentum"] = 0.9
    mod.fit(train_iter, eval_data=val_iter,
            optimizer=args.optimizer,
            optimizer_params=opt_params,
            eval_metric="acc", num_epoch=args.epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    score = mod.score(val_iter, mx.metric.Accuracy())
    print("final validation:", score)


if __name__ == "__main__":
    main()
