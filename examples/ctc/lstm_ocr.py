"""LSTM-OCR with CTC loss (reference: example/ctc/lstm_ocr_train.py).

The reference trains an LSTM over CAPTCHA image columns (sequence length =
image width) with CTC loss so the 3-4 digit label needs no per-column
alignment, then decodes greedily (collapse repeats, drop blanks). The
captcha renderer isn't available in a zero-egress image, so this example
synthesizes the same task shape: each digit is a fixed noisy column
signature of variable width, digits are separated by background gaps, and
the model must learn both segmentation and classification from the
unaligned label sequence — exactly what CTC is for.

Conventions match the reference gluon CTCLoss (blank_label='last',
gluon/loss.py): labels are zero-based, digits 0-9 map to classes 0-9 and
the last class (10) is the blank.

Run: JAX_PLATFORMS=cpu python examples/ctc/lstm_ocr.py [--steps 150]
"""
import argparse
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn

SEQ_LEN = 24          # "image width" in columns
FEAT = 16             # column height
NUM_DIGITS = (3, 4)   # like the reference's 3-4 digit captchas
CLASSES = 11          # 10 digits + trailing blank (class 10)
BLANK = CLASSES - 1


def make_generator(seed=7):
    """Per-digit column signatures + a sampler of unaligned sequences."""
    rng = np.random.RandomState(seed)
    signatures = rng.uniform(-1, 1, (10, FEAT)).astype(np.float32) * 2.0

    def sample(batch):
        x = rng.normal(0, 0.2, (batch, SEQ_LEN, FEAT)).astype(np.float32)
        labels = np.zeros((batch, max(NUM_DIGITS)), np.float32)
        lab_len = np.zeros((batch,), np.float32)
        for i in range(batch):
            n = rng.randint(NUM_DIGITS[0], NUM_DIGITS[1] + 1)
            digits = rng.randint(0, 10, n)
            pos = 1
            kept = []
            for d in digits:
                width, gap = 4, 1
                if pos + width >= SEQ_LEN:
                    break
                x[i, pos:pos + width] += signatures[d]
                kept.append(d)
                pos += width + gap
            labels[i, :len(kept)] = np.array(kept)  # zero-based (blank=last)
            lab_len[i] = len(kept)
        return x, labels, lab_len

    return sample


class OCRNet(gluon.HybridBlock):
    def __init__(self, hidden=64, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.lstm = rnn.LSTM(hidden, num_layers=2, layout="NTC")
            self.fc = nn.Dense(CLASSES, flatten=False)

    def hybrid_forward(self, F, x):
        return self.fc(self.lstm(x))   # (B, T, CLASSES)


def greedy_decode(logits):
    """argmax per step -> collapse repeats -> drop blanks (reference:
    ctc_metrics.py CtcMetrics.ctc_label)."""
    seqs = []
    for row in logits.argmax(axis=-1):
        out, prev = [], -1
        for c in row:
            if c != prev and c != BLANK:
                out.append(int(c))
            prev = c
        seqs.append(out)
    return seqs


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=350)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args(argv)

    net = OCRNet()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    sample = make_generator()

    for step in range(1, args.steps + 1):
        xb, yb, yl = sample(args.batch)
        x = mx.nd.array(xb)
        y = mx.nd.array(yb)
        with autograd.record():
            out = net(x)
            loss = ctc(out, y, None, mx.nd.array(yl))
        loss.backward()
        trainer.step(args.batch)
        if step % 25 == 0 or step == 1:
            print("step %4d  ctc loss %.3f" %
                  (step, float(loss.asnumpy().mean())))

    # sequence accuracy on fresh data, greedy decode (inference = softmax
    # path, no CTC layer — reference lstm_ocr_infer.py)
    xb, yb, yl = sample(256)
    logits = net(mx.nd.array(xb)).asnumpy()
    hits = 0
    for pred, lab, n in zip(greedy_decode(logits), yb, yl):
        if pred == [int(v) for v in lab[:int(n)]]:
            hits += 1
    acc = hits / 256
    print("sequence accuracy: %.3f" % acc)
    return acc, args.steps


if __name__ == "__main__":
    acc, steps = main()
    # convergence gate only for runs long enough to converge (sibling
    # examples' pattern, e.g. rcnn/train.py)
    sys.exit(0 if (acc > 0.6 or steps < 300) else 1)
