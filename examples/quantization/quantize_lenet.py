"""INT8 post-training quantization, end to end.

Reference analogue: example/quantization/imagenet_gen_qsym.py +
imagenet_inference.py (train fp32 → calibrate on sample batches →
quantize_model → compare fp32 vs int8 accuracy). Scaled to LeNet on
synthetic MNIST-shaped data so it runs anywhere (zero-egress / CPU);
the same flow quantizes any exported symbol on the chip.

Run: JAX_PLATFORMS=cpu python examples/quantization/quantize_lenet.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.contrib.quantization import quantize_model
from mxnet_tpu.gluon import nn


def build_lenet():
    net = nn.HybridSequential(prefix="lenet_")
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, activation="relu"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Conv2D(16, kernel_size=3, activation="relu"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Flatten(),
                nn.Dense(64, activation="relu"),
                nn.Dense(10))
    return net


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="1 epoch over 128 samples (CI smoke configs)")
    args = ap.parse_args()
    n, n_epochs = (256, 2) if args.smoke else (512, 3)
    rng = np.random.RandomState(0)
    # synthetic "MNIST": 10 gaussian class prototypes + noise
    protos = rng.uniform(-1, 1, (10, 1, 28, 28)).astype(np.float32)
    X = np.concatenate([protos[i % 10][None] + 0.1 * rng.randn(1, 1, 28, 28)
                        for i in range(n)]).astype(np.float32)
    Y = np.array([i % 10 for i in range(n)], dtype=np.float32)

    net = build_lenet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(n_epochs):
        for i in range(0, n, 64):
            x = mx.nd.array(X[i:i + 64])
            y = mx.nd.array(Y[i:i + 64])
            with mx.autograd.record():
                l = lossfn(net(x), y)
            l.backward()
            trainer.step(64)
        print("epoch %d loss %.4f" % (epoch, float(l.mean().asnumpy())))

    def accuracy(fwd):
        pred = fwd(mx.nd.array(X)).asnumpy().argmax(1)
        return (pred == Y).mean()

    fp32_acc = accuracy(net)

    # export → quantize with entropy (KL) calibration → rebind
    prefix = "/tmp/lenet_q"
    net.export(prefix, epoch=0)
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 0)
    calib = mx.io.NDArrayIter(X[:128], Y[:128], batch_size=64,
                              label_name="softmax_label")
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, ctx=mx.cpu(),
        calib_mode="entropy", calib_data=calib, num_calib_examples=128)

    mod = mx.module.Module(qsym, label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[("data", (n, 1, 28, 28))], for_training=False)
    mod.set_params(qarg, qaux, allow_missing=True)

    def q_fwd(x):
        mod.forward(mx.io.DataBatch([x], None), is_train=False)
        return mod.get_outputs()[0]

    int8_acc = accuracy(q_fwd)
    print("fp32 accuracy: %.3f   int8 accuracy: %.3f   drop: %.3f"
          % (fp32_acc, int8_acc, fp32_acc - int8_acc))
    tol = 0.06 if args.smoke else 0.02   # 1-2 epoch accuracies are noisy
    assert int8_acc > fp32_acc - tol, \
        "int8 accuracy dropped >%.0f%%" % (tol * 100)


if __name__ == "__main__":
    main()
