"""SSD training loop (reference: example/ssd/train.py). Uses the gluon SSD
model family + ImageDetIter; generates a synthetic colored-shape detection
set if no .rec is given so the example runs anywhere.

    JAX_PLATFORMS=cpu python examples/ssd/train.py --epochs 2
"""
import argparse
import os
import tempfile

import numpy as np


def synth_dataset(n=32, size=96):
    from PIL import Image

    tmp = tempfile.mkdtemp(prefix="ssd_synth_")
    rng = np.random.RandomState(0)
    imglist = []
    for i in range(n):
        arr = rng.randint(0, 80, (size, size, 3), np.uint8)
        cls = i % 2
        w = h = size // 3
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        color = (255, 40, 40) if cls == 0 else (40, 255, 40)
        arr[y0:y0 + h, x0:x0 + w] = color
        p = os.path.join(tmp, "i%d.jpg" % i)
        Image.fromarray(arr).save(p)
        imglist.append([2.0, 5.0, float(cls), x0 / size, y0 / size,
                        (x0 + w) / size, (y0 + h) / size, p])
    return imglist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--data-shape", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--network", default="tiny",
                    choices=["tiny", "resnet50_v1"])
    ap.add_argument("--samples", type=int, default=32,
                    help="synthetic dataset size (CI smoke configs)")
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo.vision import (SSDMultiBoxLoss, get_ssd,
                                                  ssd_test_tiny)

    it = mx.image.ImageDetIter(
        batch_size=args.batch_size, data_shape=(3, args.data_shape,
                                                args.data_shape),
        imglist=synth_dataset(n=args.samples), path_root="", rand_mirror=True)

    net = ssd_test_tiny(num_classes=2) if args.network == "tiny" else \
        get_ssd(args.network, args.data_shape, num_classes=2)
    net.initialize(mx.init.Xavier())
    loss_fn = SSDMultiBoxLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        it.reset()
        total, batches = 0.0, 0
        for batch in it:
            with autograd.record():
                cls_preds, loc_preds, anchors = net(batch.data[0])
                cls_t, loc_t, loc_m = net.training_targets(
                    anchors, cls_preds, batch.label[0])
                loss = loss_fn(cls_preds, loc_preds, cls_t, loc_t, loc_m)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asnumpy())
            batches += 1
        print("epoch %d: loss %.4f" % (epoch, total / max(batches, 1)))

    # decode a batch of detections
    det = net.detections(cls_preds, loc_preds, anchors)
    d = det.asnumpy()
    kept = (d[:, :, 0] >= 0).sum()
    print("detections kept after NMS (last batch):", int(kept))


if __name__ == "__main__":
    main()
