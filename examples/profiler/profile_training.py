"""Profiler walkthrough: chrome-trace capture around a training loop.

Reference analogue: example/profiler/profiler_executor.py — set_config →
set_state('run') → train → set_state('stop') → dump; opens in
chrome://tracing / perfetto. Scoped Task/Marker objects annotate phases,
and the aggregate table prints per-op totals (MXDumpAggregateStats
parity).

Run: JAX_PLATFORMS=cpu python examples/profiler/profile_training.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler
from mxnet_tpu.gluon import nn


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()
    trace_file = os.environ.get("MXTPU_PROFILE_OUT", "/tmp/mxtpu_profile.json")
    profiler.set_config(filename=trace_file, profile_all=True)
    profiler.set_state("run")

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)

    with profiler.Task("train-steps"):
        for step in range(args.steps):
            profiler.Marker("step-%d" % step).mark()
            x = mx.nd.array(rng.randn(32, 64).astype(np.float32))
            y = mx.nd.array(rng.randint(0, 10, (32,)).astype(np.float32))
            with mx.autograd.record():
                l = lossfn(net(x), y)
            l.backward()
            trainer.step(32)
    mx.nd.waitall()

    profiler.set_state("stop")
    profiler.dump()
    print("chrome trace written to %s (%d bytes) — open in "
          "chrome://tracing" % (trace_file, os.path.getsize(trace_file)))
    print("\nper-op aggregate (reference: MXDumpAggregateStats):")
    print(profiler.dumps(reset=True))


if __name__ == "__main__":
    main()
