"""LSTM language model with bucketing — the reference's
example/rnn/bucketing/lstm_bucketing.py ported with only the import line
and dataset changed (synthetic corpus instead of the Sherlock Holmes
download; pass --data to train on a real token file).

Structure kept 1:1 with the reference: mx.rnn.encode_sentences ->
BucketSentenceIter -> SequentialRNNCell of LSTMCells -> sym_gen(seq_len)
unrolling per bucket -> BucketingModule.fit with Perplexity.
"""
import argparse
import os

import numpy as np

import mxnet_tpu as mx

parser = argparse.ArgumentParser(description="Train LSTM LM with bucketing")
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-hidden", type=int, default=32)
parser.add_argument("--num-embed", type=int, default=16)
parser.add_argument("--num-epochs", type=int, default=3)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="adam")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=1e-5)
parser.add_argument("--batch-size", type=int, default=8)
parser.add_argument("--disp-batches", type=int, default=10)
parser.add_argument("--data", type=str, default=None,
                    help="tokenized text file (one sentence per line); "
                         "synthetic corpus when omitted")
parser.add_argument("--vocab-size", type=int, default=40,
                    help="synthetic corpus vocabulary size")
parser.add_argument("--sentences", type=int, default=200,
                    help="synthetic corpus size")
parser.add_argument("--buckets", type=str, default="8,12,16,20",
                    help="comma-separated bucket lengths (each bucket is "
                         "one compiled executable; fewer buckets = faster "
                         "CI smoke)")


def synthetic_corpus(rs, n_sentences, vocab_size):
    """Markov-ish token sequences so perplexity has structure to learn."""
    sents = []
    for _ in range(n_sentences):
        length = int(rs.randint(4, 18))
        tok = int(rs.randint(1, vocab_size))
        sent = []
        for _ in range(length):
            sent.append("w%d" % tok)
            tok = (tok * 2 + int(rs.randint(0, 2))) % vocab_size or 1
        sents.append(sent)
    return sents


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    if not os.path.isfile(fname):
        raise IOError("data file %s not found" % fname)
    lines = [list(filter(None, line.split(" ")))
             for line in open(fname).read().splitlines()]
    return mx.rnn.encode_sentences(lines, vocab=vocab,
                                   invalid_label=invalid_label,
                                   start_label=start_label)


def main():
    args = parser.parse_args()
    buckets = [int(b) for b in args.buckets.split(",")]
    start_label = 1
    invalid_label = 0

    if args.data:
        train_sent, vocab = tokenize_text(
            args.data, start_label=start_label,
            invalid_label=invalid_label)
        val_sent = train_sent
    else:
        rs = np.random.RandomState(0)
        raw = synthetic_corpus(rs, args.sentences, args.vocab_size)
        train_sent, vocab = mx.rnn.encode_sentences(
            raw, invalid_label=invalid_label, start_label=start_label)
        val_raw = synthetic_corpus(np.random.RandomState(1), 40,
                                   args.vocab_size)
        val_sent, _ = mx.rnn.encode_sentences(
            val_raw, vocab=vocab, invalid_label=invalid_label)

    data_train = mx.rnn.BucketSentenceIter(train_sent, args.batch_size,
                                           buckets=buckets,
                                           invalid_label=invalid_label)
    data_val = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                         buckets=buckets,
                                         invalid_label=invalid_label)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=len(vocab),
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs = stack.unroll(seq_len, inputs=embed,
                               merge_outputs=True)[0]
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=len(vocab),
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label,
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=mx.cpu(0))

    model.fit(
        train_data=data_train,
        eval_data=data_val,
        eval_metric=mx.metric.Perplexity(invalid_label),
        optimizer=args.optimizer,
        optimizer_params=dict(
            {"learning_rate": args.lr, "wd": args.wd},
            **({"momentum": args.mom} if args.optimizer == "sgd" else {})),
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches))

    score = model.score(data_val, mx.metric.Perplexity(invalid_label))
    ppl = dict(score)["perplexity" if "perplexity" in dict(score)
                      else list(dict(score))[0]]
    print("final val perplexity: %.2f (vocab %d)" % (ppl, len(vocab)))
    assert np.isfinite(ppl), "non-finite perplexity"
    if args.num_epochs >= 2:
        # one epoch is the CI smoke config; the convergence bar needs a
        # couple of epochs on the synthetic corpus
        assert ppl < len(vocab), "model did not beat the uniform baseline"


if __name__ == "__main__":
    main()
