"""LSTM word language model (reference: example/rnn/word_lm/train.py).
Trains model_zoo.word_lm.RNNModel with truncated BPTT on a synthetic
Markov-chain corpus (zero-egress stand-in for PTB).

    JAX_PLATFORMS=cpu python examples/rnn/word_lm.py --epochs 2
"""
import argparse

import numpy as np


def synth_corpus(vocab=200, length=20000, seed=0):
    """Second-order Markov text: learnable structure, ppl well below vocab."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.ones(vocab) * 0.05, size=vocab)
    data = [0]
    for _ in range(length - 1):
        data.append(rng.choice(vocab, p=trans[data[-1]]))
    return np.asarray(data, np.int32)


def batchify(data, batch_size):
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T  # (T, B)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--limit-batches", type=int, default=0,
                    help="cap bptt windows per epoch (CI smoke configs)")
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo.word_lm import RNNModel

    corpus = batchify(synth_corpus(args.vocab), args.batch_size)
    model = RNNModel(vocab_size=args.vocab, embed_size=64, hidden_size=128,
                     num_layers=1, dropout=0.0)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total, count = 0.0, 0
        state = model.begin_state(args.batch_size)
        steps = range(0, corpus.shape[0] - args.bptt - 1, args.bptt)
        if args.limit_batches:
            steps = list(steps)[:args.limit_batches]
        for t in steps:
            # TNC layout: (T, B) ids, next-token targets
            x = mx.nd.array(corpus[t:t + args.bptt], dtype="int32")
            y = mx.nd.array(corpus[t + 1:t + args.bptt + 1]
                            .astype(np.float32))
            state = [s.detach() for s in state]
            with autograd.record():
                out, state = model(x, state)
                loss = ce(out, y)
            loss.backward()
            trainer.step(args.batch_size * args.bptt)
            total += float(loss.asnumpy().mean()) * args.bptt
            count += args.bptt
        ppl = float(np.exp(total / count))
        print("epoch %d: perplexity %.1f (uniform would be %d)"
              % (epoch, ppl, args.vocab))


if __name__ == "__main__":
    main()
