#!/usr/bin/env python
"""Serving demo: export -> load/warm -> concurrent HTTP predicts -> drain.

The whole `mxnet_tpu.serving` story in one runnable script
(docs/serving.md): a HybridBlock is exported to the deployment artifact
pair, loaded into a `ModelRepository` (which binds + warms one executable
per padding bucket), served over HTTP, driven by a handful of concurrent
clients whose requests the `DynamicBatcher` coalesces, and finally
drained gracefully. Prints the coalescing evidence: requests vs. batches
dispatched, mean batch size, and that steady state compiled nothing.

  JAX_PLATFORMS=cpu python examples/serving/serve_mlp.py --requests 24
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=24,
                   help="total predict requests across all clients")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--delay-ms", type=float, default=5.0)
    args = p.parse_args(argv)

    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.serving import ModelRepository, ServingServer

    # 1. train-side artifact: a tiny MLP, exported like any deployment
    net = gluon.nn.HybridSequential(prefix="demo_")
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    x_check = mx.nd.array(np.random.RandomState(0)
                          .uniform(-1, 1, (2, 16)).astype(np.float32))
    ref = net(x_check).asnumpy()
    prefix = os.path.join(tempfile.mkdtemp(prefix="serve_mlp_"), "model")
    net.export(prefix, epoch=0)

    # 2. serve side: load + warm every bucket, start the HTTP frontend
    repo = ModelRepository()
    model = repo.load("mlp", prefix, input_shapes={"data": (16,)},
                      max_batch=args.max_batch, max_delay_ms=args.delay_ms)
    print("loaded mlp/1: buckets %s warmed in %.2fs"
          % (model.buckets, model.warm_seconds))
    server = ServingServer(repo, port=0, addr="127.0.0.1").start()
    url = "http://127.0.0.1:%d" % server.port
    print("serving on %s" % url)

    # 3. concurrent clients — the batcher coalesces their requests
    rng = np.random.RandomState(1)
    results, errors = [], []

    def client(k):
        try:
            for _ in range(k):
                x = rng.uniform(-1, 1, (1, 16)).astype(np.float32)
                body = json.dumps({"instances": x.tolist()}).encode()
                with urllib.request.urlopen(urllib.request.Request(
                        url + "/v1/models/mlp:predict", data=body),
                        timeout=30) as r:
                    results.append(json.loads(r.read())["outputs"][0])
        except Exception as e:  # demo: surface, don't hang
            errors.append(e)

    each = max(1, args.requests // args.clients)
    threads = [threading.Thread(target=client, args=(each,))
               for _ in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[0]

    # correctness spot-check against the original block
    body = json.dumps({"inputs": {"data": x_check.asnumpy().tolist()}}).encode()
    with urllib.request.urlopen(urllib.request.Request(
            url + "/v1/models/mlp:predict", data=body), timeout=30) as r:
        got = np.asarray(json.loads(r.read())["outputs"][0])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    # 4. the coalescing evidence, straight from the serving metrics
    snap = telemetry.snapshot()
    lbl = '{model="mlp/1"}'
    reqs = snap["mxtpu_serve_requests_total" + lbl]["value"]
    batches = snap["mxtpu_serve_batches_total" + lbl]["value"]
    examples = snap["mxtpu_serve_examples_total" + lbl]["value"]
    print("served %d requests in %d batches (mean batch %.2f); "
          "outputs match the source block" % (reqs, batches,
                                              examples / max(1, batches)))

    # 5. graceful drain (the SIGTERM path shares this code)
    server.drain(shutdown=True)
    print("drained; done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
