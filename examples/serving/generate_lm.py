#!/usr/bin/env python
"""Text-generation serving demo: continuous batching + paged KV cache.

The `mxnet_tpu.serving.generate` story in one runnable script
(docs/serving.md §Generation): a tiny decoder-only `TransformerLM` is
exported as a generation artifact (`save_lm`), loaded through the
`ModelRepository` (which builds the paged-KV decode engine and warms one
executable per prefill/decode bucket), served over HTTP, and driven by
concurrent ``:generate`` clients with UNEQUAL ``max_new_tokens`` — the
workload shape where requests join and leave the running decode batch at
token granularity. Prints the continuous-batching evidence: per-request
token counts, decode steps vs tokens (the achieved batch), KV-page
occupancy returning to zero, and that steady state compiled nothing.

  JAX_PLATFORMS=cpu python examples/serving/generate_lm.py --requests 8
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=8,
                   help="concurrent :generate requests")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--max-new", type=int, default=12,
                   help="largest per-request max_new_tokens")
    args = p.parse_args(argv)

    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.gluon.model_zoo.transformer import lm_mini
    from mxnet_tpu.serving import ModelRepository, ServingServer, save_lm

    # 1. train-side artifact: a tiny decoder-only LM, exported with its
    # architecture header so the serving side can rebuild it
    lm = lm_mini(vocab_size=args.vocab)
    lm.initialize(mx.init.Xavier(), ctx=mx.cpu())
    prefix = save_lm(lm, os.path.join(tempfile.mkdtemp(prefix="gen_lm_"),
                                      "lm"))

    # 2. serve side: build the paged-KV decode engine and warm every
    # prefill/decode bucket (steady-state generation never compiles)
    repo = ModelRepository()
    model = repo.load(
        "lm", prefix, generate=True,
        generate_opts=dict(num_pages=64, page_size=4, max_prompt=8,
                           max_new_tokens=max(2, args.max_new),
                           max_batch=4))
    gi = model.generate_info
    print("loaded lm/1: decode buckets %s, prefill buckets %s, "
          "kv %d pages x %d tokens, warmed in %.2fs"
          % (gi["decode_buckets"], gi["prefill_buckets"], gi["num_pages"],
             gi["page_size"], model.warm_seconds or 0.0))
    misses = telemetry.get_registry().counter("mxtpu_jit_cache_miss_total")
    base_miss = misses.value

    srv = ServingServer(repo, port=0, addr="127.0.0.1").start()
    url = "http://127.0.0.1:%d/v1/models/lm:generate" % srv.port

    # 3. concurrent greedy generations with UNEQUAL budgets: sequences
    # finish at different steps, later requests join the running batch
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(1, args.vocab,
                                            rng.randint(2, 8))]
               for _ in range(args.requests)]
    budgets = [2 + i % max(1, args.max_new - 1)
               for i in range(args.requests)]
    results = [None] * args.requests

    def client(i):
        body = json.dumps({"tokens": prompts[i],
                           "max_new_tokens": budgets[i],
                           "timeout_ms": 60000}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=90) as r:
            results[i] = json.loads(r.read())

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)

    ok = 0
    for i, res in enumerate(results):
        assert res is not None, "request %d never resolved" % i
        assert len(res["tokens"]) == budgets[i], (i, res)
        assert res["finish_reason"] == "length", res
        ok += 1
        print("  req %d: prompt %d tokens -> %s (%d generated)"
              % (i, len(prompts[i]), res["tokens"][:6], len(res["tokens"])))

    # 4. the continuous-batching + zero-compile evidence
    snap = telemetry.snapshot()
    label = '{model="lm/1"}'
    tokens = snap.get("mxtpu_serve_generated_tokens_total" + label,
                      {}).get("value", 0)
    steps = snap.get("mxtpu_serve_decode_steps_total" + label,
                     {}).get("value", 0)
    alloc = model.scheduler.allocator
    jit = misses.value - base_miss
    print("generated %d tokens in %d decode steps (mean batch %.2f); "
          "kv pages used now: %d/%d; jit compiles after warm: %d"
          % (tokens, steps, tokens / steps if steps else 0.0,
             alloc.used_pages, alloc.num_pages, jit))
    assert ok == args.requests
    assert alloc.used_pages == 0
    assert jit == 0, "steady-state decode must not compile"

    # 5. graceful drain
    srv.drain(shutdown=True)
    model.close(drain=False, timeout=0)
    print("drained; bye")
    return 0


if __name__ == "__main__":
    sys.exit(main())
