"""Sharded data-parallel training over a device mesh.

Reference analogue: example/distributed_training-horovod/gluon_mnist.py and
tools/launch.py dist_sync jobs — but TPU-native: instead of per-worker
processes exchanging gradients through a parameter server, ONE compiled XLA
step runs over the whole mesh (`parallel.DistributedTrainer`), gradients
all-reduced by the compiler over ICI. The same script spans dp-only or
dp x tp meshes; on a CPU host it uses 8 virtual devices.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python examples/distributed/train_dist.py [--tp 2]
Multi-host: python tools/launch.py -n <hosts> -- python ... (the mesh then
spans all hosts' devices via the jax.distributed rendezvous).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel axis size (rest goes to dp)")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--amp", action="store_true", help="bf16 compute")
    args = ap.parse_args()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    n = len(jax.devices())
    if n % args.tp:
        raise SystemExit("device count %d not divisible by tp=%d"
                         % (n, args.tp))
    axes = [("dp", n // args.tp)] + ([("tp", args.tp)] if args.tp > 1
                                     else [])
    mesh = make_mesh(axes)
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)),
          "on", jax.devices()[0].platform)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(256, activation="relu"),
                nn.Dense(256, activation="relu"),
                nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 100)))  # materialize deferred shapes

    trainer = DistributedTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype="bfloat16" if args.amp else None)

    rng = np.random.RandomState(0)
    W = rng.randn(100, 10).astype(np.float32)
    for step in range(args.steps):
        x = rng.randn(args.batch, 100).astype(np.float32)
        y = (x @ W).argmax(1).astype(np.float32)
        loss = trainer.step(x, y)
        if step % 10 == 0 or step == args.steps - 1:
            print("step %3d  loss %.4f" % (step, float(loss.asnumpy())))
    final = float(loss.asnumpy())
    import numpy as _np

    assert _np.isfinite(final), "non-finite loss"
    if args.steps >= 30:
        # the convergence bar needs the full default step count
        assert final < 1.5, "did not learn (loss %.3f)" % final
    print("done — global batch %d sharded over %d device(s)"
          % (args.batch, n))


if __name__ == "__main__":
    main()
