"""Expert-parallel MoE language-model training over a dp x ep mesh.

Not in the reference (MoE postdates MXNet 1.x) — this is the expert-parallel
extension SURVEY §2.3 plans as a TPU-native goal. A small causal LM whose
transformer FFN is `gluon.contrib.moe.MoEFFN` trains under
`parallel.DistributedTrainer`: the expert tables shard over the `ep` mesh
axis (parallel/sharding.py routes any parameter named "*expert*" there) and
XLA lowers the dispatch/combine einsums to all_to_alls over ICI. Top-1
(Switch) or top-k (GShard/Mixtral) routing per --top-k, with the ST-MoE
router z-loss folded into the objective.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python examples/moe/train_moe.py [--ep 4] [--top-k 2]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

VOCAB = 64
SEQ = 16


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ep", type=int, default=0,
                    help="expert-parallel axis size (0 = all devices)")
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.moe import MoEFFN
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    n = len(jax.devices())
    ep = args.ep or min(n, args.experts)
    if n % ep:
        raise SystemExit("device count %d not divisible by ep=%d" % (n, ep))
    mesh = make_mesh([("dp", n // ep), ("ep", ep)])
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)),
          "on", jax.devices()[0].platform)

    class MoELM(gluon.HybridBlock):
        """embed -> (attention-free) mixer -> MoE FFN -> tied-ish head.
        The point is the routed expert layer, not the mixer."""

        def __init__(self, units=32, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(VOCAB, units)
                self.mix = nn.Dense(units, flatten=False,
                                    activation="relu")
                self.moe = MoEFFN(units=units, hidden_size=2 * units,
                                  num_experts=args.experts,
                                  num_experts_per_token=args.top_k,
                                  z_loss_coef=1e-3, capacity_factor=2.0,
                                  return_aux=True)
                self.head = nn.Dense(VOCAB, flatten=False)

        def hybrid_forward(self, F, tokens):
            h = self.embed(tokens)
            h = h + self.mix(h)
            ffn, aux = self.moe(h)
            return self.head(h + ffn), aux

    net = MoELM()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, SEQ)))  # materialize deferred shapes

    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(out, labels):
        logits, aux = out
        return sce(logits.reshape((-1, VOCAB)),
                   labels.reshape((-1,))) + 0.01 * aux

    trainer = DistributedTrainer(net, "adam", {"learning_rate": 3e-3},
                                 loss=lm_loss, mesh=mesh)

    # synthetic next-token task: tok[t+1] = (3*tok[t] + 7) % VOCAB — fully
    # learnable by embed+head, so perplexity collapses if training works
    rng = np.random.RandomState(0)
    loss = None
    for step in range(args.steps):
        first = rng.randint(0, VOCAB, (args.batch, 1))
        seq = [first]
        for _ in range(SEQ):
            seq.append((3 * seq[-1] + 7) % VOCAB)
        toks = np.concatenate(seq, axis=1).astype(np.float32)
        loss = trainer.step(toks[:, :SEQ], toks[:, 1:SEQ + 1])
        if step % 10 == 0 or step == args.steps - 1:
            print("step %3d  loss %.4f" % (step, float(loss.asnumpy())))
    final = float(loss.asnumpy())
    assert np.isfinite(final), "non-finite loss"
    if args.steps >= 40:
        assert final < 2.0, "did not learn (loss %.3f)" % final
    print("done — %d experts (top-%d) sharded over ep=%d"
          % (args.experts, args.top_k, ep))


if __name__ == "__main__":
    main()
