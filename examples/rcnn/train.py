"""Faster R-CNN training (reference: example/rcnn/train.py + symnet/ +
symdata/ — the reference's second detection workload).

End-to-end over the real op family: backbone -> RPN heads -> Proposal
(RPN decode + NMS) -> host-side proposal-target sampling -> ROIAlign ->
RCNN cls/bbox heads, with the four standard losses (RPN cls/bbox, RCNN
cls/bbox). A synthetic colored-shape detection set keeps it runnable
anywhere (the reference trains on VOC).

    JAX_PLATFORMS=cpu python examples/rcnn/train.py --steps 20
"""
import argparse

import numpy as np


# --------------------------------------------------------------------------
# synthetic dataset: one colored rectangle per image, pixel-coord gt
# (reference symdata/loader.py feeds [cls, x1, y1, x2, y2] + im_info)
# --------------------------------------------------------------------------

def synth_batch(rng, batch, size, num_fg_classes=2):
    imgs = np.zeros((batch, 3, size, size), np.float32)
    gts = np.zeros((batch, 5), np.float32)  # [cls(1-based), x1,y1,x2,y2]
    for i in range(batch):
        imgs[i] = rng.uniform(0, 0.3, (3, size, size))
        cls = rng.randint(num_fg_classes)
        w = h = size // 3
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        imgs[i, cls, y0:y0 + h, x0:x0 + w] = 1.0  # class = hot channel
        gts[i] = [cls + 1, x0, y0, x0 + w - 1, y0 + h - 1]
    im_info = np.tile([size, size, 1.0], (batch, 1)).astype(np.float32)
    return imgs, gts, im_info


# --------------------------------------------------------------------------
# host-side target assignment (reference symdata/anchor.py AnchorGenerator
# + symnet/proposal_target.py — both run on CPU in the reference too)
# --------------------------------------------------------------------------

def _iou(boxes, gt):
    """boxes (N,4), gt (4,) -> (N,)"""
    ix1 = np.maximum(boxes[:, 0], gt[0])
    iy1 = np.maximum(boxes[:, 1], gt[1])
    ix2 = np.minimum(boxes[:, 2], gt[2])
    iy2 = np.minimum(boxes[:, 3], gt[3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    a1 = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    a2 = (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1)
    return inter / np.maximum(a1 + a2 - inter, 1e-6)


def _bbox_transform(rois, gt):
    """regression targets from rois to gt (reference symdata/bbox.py)."""
    rw = rois[:, 2] - rois[:, 0] + 1.0
    rh = rois[:, 3] - rois[:, 1] + 1.0
    rcx = rois[:, 0] + 0.5 * (rw - 1)
    rcy = rois[:, 1] + 0.5 * (rh - 1)
    gw = gt[2] - gt[0] + 1.0
    gh = gt[3] - gt[1] + 1.0
    gcx = gt[0] + 0.5 * (gw - 1)
    gcy = gt[1] + 0.5 * (gh - 1)
    return np.stack([(gcx - rcx) / rw, (gcy - rcy) / rh,
                     np.log(gw / rw), np.log(gh / rh)], axis=1)


def anchor_targets(anchors, gt_box, fg_thresh=0.5, bg_thresh=0.3,
                   num_samples=64, fg_fraction=0.5, rng=None):
    """RPN targets for ONE image: labels (N,) in {-1 ignore, 0 bg, 1 fg}
    and bbox targets (N, 4) (reference symdata/anchor.py assign)."""
    iou = _iou(anchors, gt_box)
    labels = np.full(anchors.shape[0], -1, np.float32)
    labels[iou < bg_thresh] = 0
    labels[iou >= fg_thresh] = 1
    labels[np.argmax(iou)] = 1  # best anchor is always positive
    fg = np.where(labels == 1)[0]
    bg = np.where(labels == 0)[0]
    max_fg = int(num_samples * fg_fraction)
    if len(fg) > max_fg:
        labels[rng.choice(fg, len(fg) - max_fg, replace=False)] = -1
    max_bg = num_samples - min(len(fg), max_fg)
    if len(bg) > max_bg:
        labels[rng.choice(bg, len(bg) - max_bg, replace=False)] = -1
    targets = _bbox_transform(anchors, gt_box)
    return labels, targets.astype(np.float32)


def proposal_targets(rois, gt, num_classes, num_samples=32, fg_fraction=0.5,
                     fg_thresh=0.5, rng=None):
    """Sample rois for the RCNN head of ONE image (reference
    symnet/proposal_target.py): returns (sampled rois (S,5), labels (S,),
    bbox_targets (S, 4*num_classes), bbox_weights)."""
    boxes = rois[:, 1:]
    # append gt as a guaranteed-positive roi (the reference does the same)
    boxes = np.vstack([boxes, gt[1:][None]])
    iou = _iou(boxes, gt[1:])
    fg = np.where(iou >= fg_thresh)[0]
    bg = np.where(iou < fg_thresh)[0]
    n_fg = min(len(fg), int(num_samples * fg_fraction))
    keep = []
    if n_fg > 0:
        keep.append(rng.choice(fg, n_fg, replace=False))
    n_bg = num_samples - n_fg
    if len(bg) > 0:
        keep.append(rng.choice(bg, n_bg, replace=len(bg) < n_bg))
    keep = np.concatenate(keep) if keep else np.arange(num_samples)
    boxes = boxes[keep]
    labels = np.where(iou[keep] >= fg_thresh, gt[0], 0.0).astype(np.float32)
    targets = _bbox_transform(boxes, gt[1:])
    bt = np.zeros((len(keep), 4 * num_classes), np.float32)
    bw = np.zeros_like(bt)
    for i, c in enumerate(labels.astype(int)):
        if c > 0:
            bt[i, 4 * c:4 * c + 4] = targets[i]
            bw[i, 4 * c:4 * c + 4] = 1.0
    batch_idx = np.full((len(keep), 1), rois[0, 0], np.float32)
    return (np.hstack([batch_idx, boxes]).astype(np.float32), labels,
            bt, bw)


# --------------------------------------------------------------------------
# model (reference symnet/symbol_resnet.py shape, scaled down; gluon-first)
# --------------------------------------------------------------------------

def build_net(num_classes, num_anchors, channels=32):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    class FasterRCNN(gluon.Block):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.backbone = gluon.nn.Sequential()
                for i, ch in enumerate((channels // 2, channels, channels)):
                    self.backbone.add(
                        gluon.nn.Conv2D(ch, 3, strides=2, padding=1),
                        gluon.nn.Activation("relu"))
                self.rpn_conv = gluon.nn.Conv2D(channels, 3, padding=1,
                                                activation="relu")
                self.rpn_cls = gluon.nn.Conv2D(2 * num_anchors, 1)
                self.rpn_bbox = gluon.nn.Conv2D(4 * num_anchors, 1)
                self.fc = gluon.nn.Dense(64, activation="relu")
                self.cls_head = gluon.nn.Dense(num_classes)
                self.bbox_head = gluon.nn.Dense(4 * num_classes)

        def features(self, x):
            f = self.backbone(x)
            r = self.rpn_conv(f)
            return f, self.rpn_cls(r), self.rpn_bbox(r)

        def heads(self, pooled):
            h = self.fc(pooled)
            return self.cls_head(h), self.bbox_head(h)

    return FasterRCNN()


def rpn_cls_prob(scores, num_anchors):
    """(B, 2A, H, W) logits -> softmaxed cls_prob in Proposal's layout."""
    import mxnet_tpu as mx

    b, _, h, w = scores.shape
    s = scores.reshape((b, 2, num_anchors, h, w))
    p = mx.nd.softmax(s, axis=1)
    return p.reshape((b, 2 * num_anchors, h, w))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--num-classes", type=int, default=3,
                    help="incl. background class 0")
    ap.add_argument("--roi-op", default="align",
                    choices=["align", "pool"])
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.ops.contrib import _rpn_anchors

    stride = 8  # three stride-2 convs
    scales = (2.0, 4.0)
    ratios = (1.0,)
    na = len(scales) * len(ratios)
    fh = fw = args.image_size // stride
    anchors = _rpn_anchors(fh, fw, stride, scales, ratios)

    net = build_net(args.num_classes, na)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)

    first = last = None
    for step in range(args.steps):
        imgs, gts, im_info = synth_batch(rng, args.batch_size,
                                         args.image_size,
                                         args.num_classes - 1)
        x = mx.nd.array(imgs)

        # RPN anchor targets (host, per image — reference anchor.py)
        lab_list, tgt_list = zip(*(anchor_targets(anchors, gts[i, 1:],
                                                  rng=rng)
                                   for i in range(args.batch_size)))
        rpn_labels = mx.nd.array(np.stack(lab_list))        # (B, N)
        rpn_tgts = mx.nd.array(np.stack(tgt_list))          # (B, N, 4)

        # proposals ride OUTSIDE the tape (rois are data, not a gradient
        # path — reference Proposal op has no backward)
        feat0, rpn_s0, rpn_b0 = net.features(x)
        rois_nd = mx.nd.contrib.Proposal(
            rpn_cls_prob(rpn_s0, na), rpn_b0, mx.nd.array(im_info),
            rpn_pre_nms_top_n=48, rpn_post_nms_top_n=12, threshold=0.7,
            rpn_min_size=4, scales=scales, ratios=ratios,
            feature_stride=stride)
        rois_np = rois_nd.asnumpy().reshape(args.batch_size, -1, 5)

        # RCNN targets (host — reference proposal_target.py)
        samp = [proposal_targets(rois_np[i], gts[i], args.num_classes,
                                 rng=rng)
                for i in range(args.batch_size)]
        rois_s = mx.nd.array(np.vstack([s[0] for s in samp]))
        rcnn_labels = mx.nd.array(np.concatenate([s[1] for s in samp]))
        rcnn_bt = mx.nd.array(np.vstack([s[2] for s in samp]))
        rcnn_bw = mx.nd.array(np.vstack([s[3] for s in samp]))

        with autograd.record():
            feat, rpn_scores, rpn_deltas = net.features(x)

            # RPN losses over the anchor grid
            b = args.batch_size
            sc = rpn_scores.reshape((b, 2, na, fh, fw)) \
                .transpose((0, 2, 3, 4, 1)).reshape((-1, 2))
            lab = rpn_labels.reshape((-1,))
            keep = lab >= 0
            rpn_cls_loss = (ce(sc, mx.nd.maximum(lab, 0)) * keep).sum() \
                / mx.nd.maximum(keep.sum(), 1)
            de = rpn_deltas.reshape((b, na, 4, fh, fw)) \
                .transpose((0, 1, 3, 4, 2)).reshape((b, -1, 4))
            fgm = (rpn_labels == 1).expand_dims(2)
            rpn_bbox_loss = (mx.nd.smooth_l1(de - rpn_tgts, scalar=3.0)
                             * fgm).sum() / mx.nd.maximum(fgm.sum(), 1)

            # RCNN head over pooled rois
            roi_fn = mx.nd.contrib.ROIAlign if args.roi_op == "align" \
                else mx.nd.ROIPooling
            pooled = roi_fn(feat, rois_s, pooled_size=(3, 3),
                            spatial_scale=1.0 / stride)
            cls_logits, bbox_pred = net.heads(pooled.reshape(
                (pooled.shape[0], -1)))
            rcnn_cls_loss = ce(cls_logits, rcnn_labels).mean()
            rcnn_bbox_loss = (mx.nd.smooth_l1(
                (bbox_pred - rcnn_bt) * rcnn_bw, scalar=1.0)).sum() \
                / mx.nd.maximum(rcnn_bw.sum(), 1)

            loss = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss \
                + rcnn_bbox_loss
        loss.backward()
        trainer.step(args.batch_size)

        cur = float(loss.asnumpy())
        if first is None:
            first = cur
        last = cur
        if step % 5 == 0 or step == args.steps - 1:
            print("step %3d  loss %.4f (rpn_cls %.3f rpn_bbox %.3f "
                  "rcnn_cls %.3f rcnn_bbox %.3f)"
                  % (step, cur, float(rpn_cls_loss.asnumpy()),
                     float(rpn_bbox_loss.asnumpy()),
                     float(rcnn_cls_loss.asnumpy()),
                     float(rcnn_bbox_loss.asnumpy())))

    print("loss %.4f -> %.4f" % (first, last))
    assert np.isfinite(last), "training diverged"
    if args.steps >= 20:
        # short CI smokes (< 20 steps) can't guarantee a monotone dip on
        # every seed; the convergence claim belongs to the full config
        assert last < first, "training did not reduce the loss"

    # inference demo (reference demo.py): proposals -> heads -> decode the
    # top-scoring detection and check it lands on the object
    imgs, gts, im_info = synth_batch(rng, 1, args.image_size,
                                     args.num_classes - 1)
    x = mx.nd.array(imgs)
    feat, rpn_s, rpn_b = net.features(x)
    rois = mx.nd.contrib.Proposal(
        rpn_cls_prob(rpn_s, na), rpn_b, mx.nd.array(im_info),
        rpn_pre_nms_top_n=48, rpn_post_nms_top_n=8, threshold=0.7,
        rpn_min_size=4, scales=scales, ratios=ratios,
        feature_stride=stride)
    roi_fn = mx.nd.contrib.ROIAlign if args.roi_op == "align" \
        else mx.nd.ROIPooling
    pooled = roi_fn(feat, rois, pooled_size=(3, 3),
                    spatial_scale=1.0 / stride)
    cls_logits, bbox_pred = net.heads(pooled.reshape((pooled.shape[0], -1)))
    probs = mx.nd.softmax(cls_logits, axis=-1).asnumpy()
    fg = probs[:, 1:]
    best = np.unravel_index(fg.argmax(), fg.shape)
    print("top detection: roi %d class %d p=%.3f (gt class %d)"
          % (best[0], best[1] + 1, fg[best], int(gts[0, 0])))


if __name__ == "__main__":
    main()
