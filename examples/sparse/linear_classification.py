"""Sparse linear classification: CSR data + row_sparse weights.

Reference analogue: example/sparse/linear_classification/train.py — a
linear model over high-dimensional sparse features (CSR batches), with
row_sparse weight/grad so the optimizer touches only the rows each batch
hits (lazy update), and kvstore row_sparse_pull fetching just those rows.

Run: JAX_PLATFORMS=cpu python examples/sparse/linear_classification.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray import sparse

DIM, ACTIVE, BATCH = 1000, 12, 32


def synth_batch(rng, w_true):
    """CSR batch: ACTIVE random features per row."""
    data, indices, indptr, ys = [], [], [0], []
    for _ in range(BATCH):
        cols = rng.choice(DIM, ACTIVE, replace=False)
        vals = rng.randn(ACTIVE).astype(np.float32)
        data.extend(vals)
        indices.extend(cols)
        indptr.append(len(data))
        ys.append(1.0 if vals @ w_true[cols] > 0 else 0.0)
    x = sparse.csr_matrix(
        (np.array(data, np.float32), np.array(indices, np.int64),
         np.array(indptr, np.int64)), shape=(BATCH, DIM))
    return x, mx.nd.array(np.array(ys, np.float32))


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    args = ap.parse_args()
    rng = np.random.RandomState(0)
    w_true = rng.randn(DIM).astype(np.float32)

    # dense master weight + row_sparse gradients: the optimizer's lazy
    # update touches only the rows each batch hits (reference keeps the
    # weight row_sparse on the PS; here the chip holds it dense in HBM and
    # sparsity lives in the gradient/update path)
    weight = mx.nd.zeros((DIM, 1))
    weight.attach_grad(stype="row_sparse")
    opt = mx.optimizer.create("adagrad", learning_rate=0.5)
    state = opt.create_state(0, weight)

    kv = mx.kv.create("local")
    kv.init(0, weight)

    correct = total = 0
    for step in range(args.steps):
        if step == max(args.steps - 30, args.steps * 4 // 5):
            correct = total = 0  # measure post-convergence accuracy
        x, y = synth_batch(rng, w_true)
        with autograd.record():
            logits = sparse.dot(x, weight).reshape((BATCH,))
            # logistic loss
            loss = mx.nd.log(1 + mx.nd.exp(-(2 * y - 1) * logits)).mean()
        loss.backward()
        assert weight.grad.stype == "row_sparse", weight.grad.stype
        opt.update(0, weight, weight.grad, state)
        kv.push(0, weight)

        pred = (logits.asnumpy() > 0).astype(np.float32)
        correct += (pred == y.asnumpy()).sum()
        total += BATCH
        if step % 30 == 0 or step == 149:
            print("step %3d  loss %.4f  running acc %.3f  nnz rows %d"
                  % (step, float(loss.asnumpy()), correct / total,
                     weight.grad.indices.shape[0]))

    # row_sparse pull of just-seen rows (the reference's demo op)
    rows = mx.nd.array(np.arange(8, dtype=np.float32))
    out = mx.nd.zeros((DIM, 1)).tostype("row_sparse")
    kv.row_sparse_pull(0, out=out, row_ids=rows)
    acc = correct / total
    print("final accuracy %.3f" % acc)
    bar = 0.8 if args.steps >= 150 else 0.6   # smoke runs train less
    assert acc > bar, "sparse linear model failed to learn (acc %.3f)" % acc


if __name__ == "__main__":
    main()
