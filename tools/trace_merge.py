"""Merge per-process traces into ONE perfetto timeline.

Two input kinds, freely mixable:

  * chrome-trace profiler dumps (`profiler.dump()` — pid = rank, named
    thread lanes), the original PR-3 path;
  * telemetry JSONL files carrying distributed-tracing span lines
    (``{"kind": "span", ...}`` — telemetry/tracing.py), including
    `launcher-events.jsonl` span records. One serving request or training
    step becomes a span tree across every process it touched.

    python tools/trace_merge.py -o merged.json telemetry-rank0-*.jsonl ...
    python tools/trace_merge.py -o merged.json rank0.json rank1.json ...

Guarantees on the output:
  * every chrome-trace input occupies a DISTINCT pid (colliding inputs —
    e.g. single-process dumps that all stamped pid 0 — are remapped to the
    first free pid, preserving each file's internal pid->tid structure);
  * span inputs are grouped into one process lane per (component, os-pid)
    — a pooled serving request renders as server / router / worker lanes
    — labeled via `process_name`/`process_sort_index` metadata; span
    trace/span/parent ids ride in each event's `args` so perfetto's flow
    UI and `--trace <id>` filtering work;
  * OLD-format telemetry JSONL (span-less, pre-tracing) is tolerated: the
    file contributes zero events and is reported, not fatal;
  * timestamps pass through untouched by default (profiler clocks are
    relative to process start; span clocks are epoch wall time — same-host
    processes line up at µs granularity); `--align-start` rebases every
    input so its earliest event sits at t=0 for clock-skewed hosts.

Stdlib-only (safe on a login host with no jax).
"""
from __future__ import annotations

import argparse
import json
import sys


def load_trace(path):
    """Read one input file. Returns ``("chrome", events)`` for a
    chrome-trace JSON (object form {traceEvents: [...]} or the bare array
    form) or ``("spans", records)`` for a telemetry/launcher JSONL with
    span lines (possibly empty — old-format files are span-less)."""
    with open(path) as f:
        text = f.read()
    if path.endswith(".jsonl"):
        return "spans", _spans_of_jsonl(text, path)
    try:
        data = json.loads(text)
    except ValueError:
        return "spans", _spans_of_jsonl(text, path)
    if isinstance(data, list):
        return "chrome", data
    if isinstance(data, dict) and isinstance(data.get("traceEvents"), list):
        return "chrome", data["traceEvents"]
    if isinstance(data, dict) and "kind" in data:
        # a one-line JSONL (single flush) parses as a bare JSON object
        return "spans", _spans_of_jsonl(text, path)
    raise ValueError("%s: neither a chrome trace (no traceEvents array) "
                     "nor a telemetry JSONL" % path)


def _span_of_record(rec):
    """Normalize the two span-record shapes: telemetry's top-level
    ``{"kind": "span", ...}`` and the launcher's
    ``{"kind": "event", "event": "span", "fields": {...}}``."""
    if rec.get("kind") == "span":
        return rec
    if rec.get("kind") == "event" and rec.get("event") == "span":
        fields = dict(rec.get("fields") or {})
        fields.setdefault("ts", rec.get("ts"))
        fields.setdefault("pid", rec.get("pid", 0))
        return fields
    return None


def _spans_of_jsonl(text, path):
    spans, bad = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            bad += 1  # torn tail line from a live writer: skip, don't die
            continue
        span = _span_of_record(rec)
        if span is not None and isinstance(span.get("ts"), (int, float)):
            spans.append(span)
    if bad:
        sys.stderr.write("[trace_merge] %s: skipped %d unparseable "
                         "line(s)\n" % (path, bad))
    return spans


def _pids_of(events):
    return {ev.get("pid", 0) for ev in events}


def _min_ts(events):
    ts = [ev["ts"] for ev in events
          if isinstance(ev.get("ts"), (int, float)) and ev.get("ph") != "M"]
    return min(ts) if ts else 0


def _alloc_pid(used, want=0):
    new = want
    while new in used:
        new += 1
    used.add(new)
    return new


def merge_chrome(events, used_pids, merged, align_start):
    """One chrome-trace input: remap colliding pids, label lanes."""
    pids = sorted(_pids_of(events))
    remap = {pid: _alloc_pid(used_pids, pid) for pid in pids}
    base_ts = _min_ts(events) if align_start else 0
    for pid in pids:
        merged.append({"ph": "M", "name": "process_name",
                       "pid": remap[pid], "tid": 0,
                       "args": {"name": "rank %d" % remap[pid]}})
        merged.append({"ph": "M", "name": "process_sort_index",
                       "pid": remap[pid], "tid": 0,
                       "args": {"sort_index": remap[pid]}})
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") in (
                "process_name", "process_sort_index"):
            continue  # superseded by the labels above
        out = dict(ev)
        out["pid"] = remap.get(ev.get("pid", 0), ev.get("pid", 0))
        if base_ts and isinstance(out.get("ts"), (int, float)):
            out["ts"] = out["ts"] - base_ts
        merged.append(out)


def merge_spans(spans, used_pids, merged, align_start, lanes,
                trace_filter=None):
    """Span records (already normalized) from ONE input file: each
    (component, os-pid) pair becomes a process lane shared across input
    files (server/router/worker lanes), threads become tids."""
    if trace_filter:
        spans = [s for s in spans if s.get("trace") == trace_filter]
    base_ts = min((s["ts"] for s in spans), default=0) if align_start else 0
    for span in spans:
        component = span.get("component") or "rank %s" % span.get("rank", 0)
        lane_key = (component, span.get("pid", 0))
        lane = lanes.get(lane_key)
        if lane is None:
            pid = _alloc_pid(used_pids, 100 + len(lanes))
            lane = lanes[lane_key] = {"pid": pid, "tids": {}}
            merged.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {
                               "name": "%s (pid %s)" % lane_key}})
            merged.append({"ph": "M", "name": "process_sort_index",
                           "pid": pid, "tid": 0,
                           "args": {"sort_index": pid}})
        thread = str(span.get("thread") or "main")
        tid = lane["tids"].get(thread)
        if tid is None:
            tid = lane["tids"][thread] = len(lane["tids"]) + 1
            merged.append({"ph": "M", "name": "thread_name",
                           "pid": lane["pid"], "tid": tid,
                           "args": {"name": thread}})
        args = {"trace": span.get("trace"), "span": span.get("span"),
                "parent": span.get("parent")}
        args.update(span.get("attrs") or {})
        merged.append({
            "ph": "X",
            "name": span.get("name", "span"),
            "cat": component,
            "ts": (span["ts"] - base_ts) * 1e6,
            "dur": span.get("dur_us", 0),
            "pid": lane["pid"],
            "tid": tid,
            "args": args,
        })


def merge_traces(inputs, align_start=False, trace_filter=None):
    """Merge parsed inputs — a list of ``(kind, payload)`` from
    `load_trace` — into one trace dict."""
    used_pids = set()
    merged = []
    lanes = {}  # (component, os pid) -> {pid, tids} — shared across files
    for kind, payload in inputs:
        if kind == "chrome":
            merge_chrome(payload, used_pids, merged, align_start)
        else:
            merge_spans(payload, used_pids, merged, align_start, lanes,
                        trace_filter=trace_filter)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge per-process mxnet_tpu traces (profiler dumps "
                    "and/or telemetry span JSONL) into one perfetto-"
                    "loadable chrome trace")
    parser.add_argument("inputs", nargs="+",
                        help="profiler dump .json and/or telemetry .jsonl "
                             "files (rank order = argument order)")
    parser.add_argument("-o", "--output", required=True,
                        help="merged trace path")
    parser.add_argument("--align-start", action="store_true",
                        help="rebase each file's earliest event to t=0 "
                             "(clock-skewed hosts)")
    parser.add_argument("--trace", default=None, metavar="TRACE_ID",
                        help="keep only spans of this trace id (renders "
                             "one request/step; profiler inputs are "
                             "unaffected)")
    args = parser.parse_args(argv)

    inputs = []
    for p in args.inputs:
        kind, payload = load_trace(p)
        if kind == "spans" and not payload:
            sys.stderr.write("[trace_merge] %s: no span records (old-"
                             "format/span-less file) — skipped\n" % p)
            continue
        inputs.append((kind, payload))
    merged = merge_traces(inputs, align_start=args.align_start,
                          trace_filter=args.trace)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    pids = sorted(_pids_of(merged["traceEvents"]))
    sys.stderr.write(
        "[trace_merge] wrote %s: %d events across %d process lanes "
        "(pids %s)\n" % (args.output, len(merged["traceEvents"]),
                         len(pids), pids))
    return 0


if __name__ == "__main__":
    sys.exit(main())
