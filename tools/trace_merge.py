"""Merge per-rank chrome-trace profiler dumps into ONE perfetto timeline.

Each rank of a distributed run writes its own `profiler.dump()` file
(pid = rank, named thread lanes — mxnet_tpu/profiler.py). This tool merges
them into a single chrome://tracing / perfetto.dev -loadable JSON whose
process lanes are the ranks:

    python tools/trace_merge.py -o merged.json rank0.json rank1.json ...

Guarantees on the output:
  * every input file occupies a DISTINCT pid (inputs that collide — e.g.
    single-process dumps that all stamped pid 0, or pre-telemetry traces —
    are remapped to the first free pid, preserving each file's internal
    pid->tid structure);
  * each process lane carries `process_name` ("rank N") and
    `process_sort_index` metadata, so perfetto orders and labels them;
  * timestamps are passed through untouched by default (profiler clocks
    are already relative to process start, which lines ranks up at step
    granularity); `--align-start` rebases every file so its earliest event
    sits at t=0 for clock-skewed hosts.

Stdlib-only (safe on a login host with no jax).
"""
from __future__ import annotations

import argparse
import json
import sys


def load_trace(path):
    """Read one chrome-trace JSON (object form {traceEvents: [...]} or the
    bare array form) and return its event list."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return data
    events = data.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("%s: not a chrome trace (no traceEvents array)"
                         % path)
    return events


def _pids_of(events):
    return {ev.get("pid", 0) for ev in events}


def _min_ts(events):
    ts = [ev["ts"] for ev in events
          if isinstance(ev.get("ts"), (int, float)) and ev.get("ph") != "M"]
    return min(ts) if ts else 0


def merge_traces(event_lists, align_start=False):
    """Merge several per-process event lists into one trace dict.

    Each input keeps its own pid (the profiler stamps pid=rank); when two
    inputs claim the same pid, later ones are remapped to the first unused
    pid so no two files ever share a process lane. process_name /
    process_sort_index metadata is (re)written per lane as "rank <pid>"."""
    used_pids = set()
    merged = []
    for events in event_lists:
        pids = sorted(_pids_of(events))
        remap = {}
        for pid in pids:
            new = pid
            while new in used_pids:
                new += 1
            remap[pid] = new
            used_pids.add(new)
        base_ts = _min_ts(events) if align_start else 0
        for pid in pids:
            merged.append({"ph": "M", "name": "process_name",
                           "pid": remap[pid], "tid": 0,
                           "args": {"name": "rank %d" % remap[pid]}})
            merged.append({"ph": "M", "name": "process_sort_index",
                           "pid": remap[pid], "tid": 0,
                           "args": {"sort_index": remap[pid]}})
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") in (
                    "process_name", "process_sort_index"):
                continue  # superseded by the labels above
            out = dict(ev)
            out["pid"] = remap.get(ev.get("pid", 0), ev.get("pid", 0))
            if base_ts and isinstance(out.get("ts"), (int, float)):
                out["ts"] = out["ts"] - base_ts
            merged.append(out)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Merge per-rank mxnet_tpu profiler dumps into one "
                    "perfetto-loadable chrome trace")
    parser.add_argument("inputs", nargs="+",
                        help="per-rank profile.json files (rank order = "
                             "argument order)")
    parser.add_argument("-o", "--output", required=True,
                        help="merged trace path")
    parser.add_argument("--align-start", action="store_true",
                        help="rebase each file's earliest event to t=0 "
                             "(clock-skewed hosts)")
    args = parser.parse_args(argv)

    event_lists = [load_trace(p) for p in args.inputs]
    merged = merge_traces(event_lists, align_start=args.align_start)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    pids = sorted(_pids_of(merged["traceEvents"]))
    sys.stderr.write(
        "[trace_merge] wrote %s: %d events across %d process lanes "
        "(pids %s)\n" % (args.output, len(merged["traceEvents"]),
                         len(pids), pids))
    return 0


if __name__ == "__main__":
    sys.exit(main())
