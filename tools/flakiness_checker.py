#!/usr/bin/env python
"""Run a test many times with varying seeds to detect flakiness
(reference: tools/flakiness_checker.py over nose; here over pytest).

Usage:
    python tools/flakiness_checker.py tests/test_operator.py::test_dropout \\
        [--num-trials 50] [--seed N]

One pytest process per trial so every trial gets a DISTINCT seed
(pytest dedupes repeated node ids, and in-process repeats would share the
env seed). Exit code is non-zero on the first failing trial; the failing
seed is printed for replay via MXNET_TEST_SEED.
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys


def main():
    ap = argparse.ArgumentParser(description="pytest flakiness checker")
    ap.add_argument("test", help="pytest node id, e.g. tests/test_x.py::test_y")
    ap.add_argument("--num-trials", type=int, default=50)
    ap.add_argument("--seed", type=int, default=None,
                    help="fixed seed (default: a fresh seed per trial)")
    args = ap.parse_args()

    rng = random.Random()
    for trial in range(1, args.num_trials + 1):
        seed = args.seed if args.seed is not None else rng.randrange(2**31)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MXTPU_TEST_SEED=str(seed),
                   MXNET_TEST_SEED=str(seed))
        res = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", args.test],
            env=env, capture_output=True, text=True)
        if res.returncode != 0:
            print(res.stdout[-2000:])
            print("FLAKY: trial %d/%d failed (MXNET_TEST_SEED=%d)"
                  % (trial, args.num_trials, seed))
            return 1
        print("trial %d/%d ok (seed %d)" % (trial, args.num_trials, seed))
    print("stable: %d trials passed" % args.num_trials)
    return 0


if __name__ == "__main__":
    sys.exit(main())
