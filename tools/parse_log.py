#!/usr/bin/env python
"""Parse a training log into a markdown table (reference: tools/parse_log.py
— same CLI and the same `Epoch[N] Train-<metric>=V` / `Validation-<metric>=V`
/ `Time cost=T` line format that module.fit()/model.fit() emit here,
mxnet_tpu/module/base_module.py:187-204)."""
from __future__ import annotations

import argparse
import re


def parse(lines, metric_names):
    """Returns {epoch: {column: value}} for train/val metrics + time."""
    pats = []
    for s in metric_names:
        pats.append(("train-" + s,
                     re.compile(r".*Epoch\[(\d+)\] Train-" + re.escape(s)
                                + r".*=([-+.eE\d]+)")))
        pats.append(("val-" + s,
                     re.compile(r".*Epoch\[(\d+)\] Validation-" + re.escape(s)
                                + r".*=([-+.eE\d]+)")))
    pats.append(("time", re.compile(r".*Epoch\[(\d+)\] Time.*=([-+.eE\d]+)")))

    data = {}
    for line in lines:
        for col, pat in pats:
            m = pat.match(line)
            if m is not None:
                try:
                    epoch, val = int(m.group(1)), float(m.group(2))
                except ValueError:
                    continue  # malformed numeric (e.g. bare sign)
                data.setdefault(epoch, {})[col] = val
                break
    return data


def to_markdown(data, metric_names):
    cols = []
    for s in metric_names:
        cols += ["train-" + s, "val-" + s]
    cols.append("time")
    out = ["| epoch | " + " | ".join(cols) + " |",
           "| --- |" + " --- |" * len(cols)]
    for epoch in sorted(data):
        row = data[epoch]
        out.append("| %d | %s |" % (
            epoch, " | ".join("%.6g" % row[c] if c in row else ""
                              for c in cols)))
    return "\n".join(out)


def main():
    ap = argparse.ArgumentParser(description="Parse training output log")
    ap.add_argument("logfile", nargs=1, type=str)
    ap.add_argument("--format", type=str, default="markdown",
                    choices=["markdown", "none"])
    ap.add_argument("--metric-names", type=str, nargs="+",
                    default=["accuracy"])
    args = ap.parse_args()
    with open(args.logfile[0]) as f:
        data = parse(f.readlines(), args.metric_names)
    if args.format == "markdown":
        print(to_markdown(data, args.metric_names))


if __name__ == "__main__":
    main()
