#!/usr/bin/env python
"""serve: launch the mxnet_tpu dynamic-batching inference server
(docs/serving.md).

Loads one or more deployment artifacts into a `ModelRepository`, warms
every padding bucket (so steady-state traffic never compiles), and serves
the `/v1/models` HTTP surface until SIGTERM — which drains queued and
in-flight requests before exiting 0.

Model specs (repeatable ``--model``):

  name=PREFIX@input=DIMS[:dtype][,input2=...]   export prefix
      (PREFIX-symbol.json + PREFIX-NNNN.params; DIMS are the PER-EXAMPLE
      dims, 'x'-separated, batch dim excluded)
  name=PATH.mxc                                  compiled AOT artifact
      (geometry frozen at build; its batch size is the padding bucket)
  name=PREFIX@generate                           generation LM artifact
      (PREFIX-lmconfig.json + PREFIX-lm.params from `serving.save_lm`;
      served via the continuous-batching decode scheduler and
      ``POST /v1/models/<name>:generate`` — docs/serving.md §Generation)

Examples:

  python tools/serve.py --model mlp=/models/mlp/model@data=8
  python tools/serve.py --model rn18=/models/rn18/model@data=3x224x224 \\
                        --model rn18mxc=/models/rn18.mxc --port 8500
  python tools/serve.py --model lm=/models/lm/model@generate --replicas 2

Knobs default to the typed ``MXTPU_SERVE_*`` registry (docs/env_vars.md);
CLI flags override per process.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def parse_model_spec(spec):
    """``name=path[@in=DIMS[:dtype][,in2=...]]`` -> (name, path, shapes,
    dtypes); shapes/dtypes are None for compiled artifacts. The
    ``@generate`` signature marks a generation LM artifact (shapes =
    the string ``"generate"``)."""
    if "=" not in spec:
        raise ValueError("model spec %r needs name=path" % spec)
    name, rest = spec.split("=", 1)
    if "@" not in rest:
        return name, rest, None, None
    path, sig = rest.split("@", 1)
    if sig == "generate":
        return name, path, "generate", None
    shapes, dtypes = {}, {}
    for part in sig.split(","):
        if "=" not in part:
            raise ValueError("input spec %r needs input=DIMS" % part)
        iname, dims = part.split("=", 1)
        if ":" in dims:
            dims, dtype = dims.split(":", 1)
            dtypes[iname] = dtype
        shapes[iname] = tuple(int(d) for d in dims.split("x") if d)
    return name, path, shapes, (dtypes or None)


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--model", action="append", required=True,
                   metavar="NAME=PATH[@IN=DIMS[:DTYPE],...]",
                   help="artifact to serve (repeatable)")
    p.add_argument("--port", type=int, default=None,
                   help="HTTP port (default MXTPU_SERVE_PORT; 0 = free port)")
    p.add_argument("--addr", default="0.0.0.0")
    p.add_argument("--max-batch", type=int, default=None,
                   help="override MXTPU_SERVE_MAX_BATCH")
    p.add_argument("--delay-ms", type=float, default=None,
                   help="override MXTPU_SERVE_MAX_DELAY_MS")
    p.add_argument("--queue-depth", type=int, default=None,
                   help="override MXTPU_SERVE_QUEUE_DEPTH")
    p.add_argument("--no-warm", action="store_true",
                   help="skip bucket warmup at load (first requests compile)")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica worker processes per model (default "
                        "MXTPU_SERVE_REPLICAS; 0 = in-process, no pool). "
                        "N >= 1 serves through a supervised pool with "
                        "health-checked failover (docs/serving.md "
                        "resilience)")
    p.add_argument("--autoscale", action="store_true",
                   default=None,
                   help="arm the elastic autoscaler (default "
                        "MXTPU_AUTOSCALE): SLO-breach scale-up / idle "
                        "scale-down of every pooled model, in place "
                        "(docs/serving.md §Autoscaling)")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="per-model autoscaling floor (default "
                        "MXTPU_AUTOSCALE_MIN_REPLICAS)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="per-model autoscaling ceiling (default "
                        "MXTPU_AUTOSCALE_MAX_REPLICAS)")
    p.add_argument("--pin", action="store_true",
                   help="pin the loaded models: exempt from "
                        "budget-pressure eviction")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="[serve] %(asctime)s %(levelname)s %(message)s")
    log = logging.getLogger("mxnet_tpu.serving")

    from mxnet_tpu import env as _env
    from mxnet_tpu.serving import ModelRepository, ServingServer

    replicas = args.replicas
    if replicas is None:
        replicas = _env.get("MXTPU_SERVE_REPLICAS")
    repo = ModelRepository()
    for spec in args.model:
        name, path, shapes, dtypes = parse_model_spec(spec)
        log.info("loading %s from %s%s ...", name, path,
                 " (%d replicas)" % replicas if replicas else "")
        scale_kw = dict(min_replicas=args.min_replicas,
                        max_replicas=args.max_replicas, pinned=args.pin)
        if shapes == "generate":
            opts = {}
            if args.max_batch is not None:
                opts["max_batch"] = args.max_batch
            model = repo.load(name, path, generate=True,
                              generate_opts=opts,
                              queue_depth=args.queue_depth,
                              replicas=replicas, **scale_kw)
            log.info("loaded %s/%d (generate) %s warm=%.2fs", model.name,
                     model.version, model.generate_info.get("decode_buckets"),
                     model.warm_seconds or 0.0)
            continue
        model = repo.load(name, path, input_shapes=shapes,
                          input_dtypes=dtypes, max_batch=args.max_batch,
                          max_delay_ms=args.delay_ms,
                          queue_depth=args.queue_depth,
                          warm=not args.no_warm, replicas=replicas,
                          **scale_kw)
        log.info("loaded %s/%d buckets=%s warm=%.2fs", model.name,
                 model.version, model.buckets, model.warm_seconds or 0.0)

    server = ServingServer(repo, port=args.port, addr=args.addr)
    autoscale = args.autoscale
    if autoscale is None:
        autoscale = _env.get("MXTPU_AUTOSCALE")
    if autoscale:
        from mxnet_tpu.serving import Autoscaler

        server.attach_autoscaler(Autoscaler(repo))
        log.info("autoscaler armed (interval %.0fms, up after %d breached "
                 "windows, idle scale-down after %.0fs)",
                 _env.get("MXTPU_AUTOSCALE_INTERVAL_MS"),
                 _env.get("MXTPU_AUTOSCALE_UP_WINDOWS"),
                 _env.get("MXTPU_AUTOSCALE_IDLE_S"))
    server.install_signal_handlers()
    log.info("serving %s on %s:%d (SIGTERM drains and exits 0)",
             repo.names(), args.addr, server.port)
    server.serve_forever()  # returns after the SIGTERM drain
    if server.drain_failed:
        # the drain timed out (MXTPU_SERVE_DRAIN_TIMEOUT_MS) and stranded
        # requests were force-completed 503 — tell the supervisor
        log.error("drain timed out; stranded requests were 503ed")
        return 1
    log.info("drained; bye")
    return 0


if __name__ == "__main__":
    sys.exit(main())
