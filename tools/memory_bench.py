#!/usr/bin/env python
"""memory_bench: committed CPU evidence for the memory-observability row
(docs/observability.md §Memory).

Three checks, one JSON row (``bench_capture.sh`` archives it as
``BENCH_<tag>_memory.json``):

  1. **footprint attribution** — load a model through `ModelRepository`
     with the persistent compile cache armed; its per-bucket
     `memory_analysis()` figures and total device footprint must be
     computed (the number ``MXTPU_SERVE_MEMORY_BUDGET`` enforces).
  2. **budget admission** — reload under a budget SMALLER than the
     measured footprint (must be rejected with the typed
     `MemoryBudgetError`, HTTP 507) and under a budget larger (must
     publish), plus the ``warn:`` canary mode (must publish).
  3. **donation verifier** — one `DistributedTrainer` fused step; the
     fill-hook verifier must report the donated param/optimizer buffers
     actually aliased (ROADMAP item 1's invariant as a measured number).

Per-phase peak RSS rides every stage. Exit 0 only when all three checks
hold.

    JAX_PLATFORMS=cpu python tools/memory_bench.py > BENCH_memory.json
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def log(msg):
    sys.stderr.write("[memory_bench] %s\n" % msg)
    sys.stderr.flush()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--max-batch", type=int, default=8)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="memory_bench_")
    # armed persistent tier: memory figures come from the AOT fill hook
    # and survive in the MXTPUEXE1 headers
    os.environ["MXTPU_COMPILE_CACHE"] = os.path.join(workdir, "cache")
    os.environ.pop("MXTPU_SERVE_MEMORY_BUDGET", None)

    import numpy as np

    import mxnet_tpu  # noqa: F401  (package init pins platform handling)
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import loss as gloss, nn
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh
    from mxnet_tpu.serving import MemoryBudgetError, ModelRepository
    from mxnet_tpu.telemetry import memory as tm_memory

    from serve_bench import _build_mlp  # noqa: E402

    mem_phases = {"start": tm_memory.read_process_memory()}

    log("building mlp ...")
    prefix, input_shapes = _build_mlp(workdir)

    # -- 1: footprint attribution ------------------------------------------
    repo = ModelRepository()
    model = repo.load("m", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch)
    footprint = model.memory_bytes
    per_bucket = {str(b): f for b, f in sorted(model.bucket_memory.items())}
    mem_phases["loaded"] = tm_memory.read_process_memory()
    log("footprint %s bytes across buckets %s" % (footprint, model.buckets))
    repo.unload("m", timeout=5)

    # -- 2: budget admission ------------------------------------------------
    rejected = accepted = warn_accepted = False
    reject_status = None
    if footprint:
        os.environ["MXTPU_SERVE_MEMORY_BUDGET"] = str(footprint // 2)
        try:
            repo.load("m", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch)
        except MemoryBudgetError as e:
            rejected = True
            reject_status = e.status
            log("over-budget load rejected (HTTP %d): %s" % (e.status, e))
        os.environ["MXTPU_SERVE_MEMORY_BUDGET"] = "warn:%d" % (footprint // 2)
        try:
            repo.load("m", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch)
            warn_accepted = True
            repo.unload("m", timeout=5)
            log("warn-mode over-budget load published (canary posture)")
        except MemoryBudgetError:
            pass
        os.environ["MXTPU_SERVE_MEMORY_BUDGET"] = str(footprint * 4)
        try:
            m2 = repo.load("m", prefix, input_shapes=input_shapes,
                           max_batch=args.max_batch)
            accepted = m2.memory_bytes == footprint
            repo.unload("m", timeout=5)
            log("within-budget load accepted (footprint stable: %s)"
                % accepted)
        except MemoryBudgetError:
            pass
        os.environ.pop("MXTPU_SERVE_MEMORY_BUDGET", None)
    mem_phases["budget_checks"] = tm_memory.read_process_memory()

    # -- 3: donation verifier -----------------------------------------------
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize()
    net(nd.zeros((8, 64)))
    tr = DistributedTrainer(net, "sgd", {"learning_rate": 0.1},
                            loss=gloss.SoftmaxCrossEntropyLoss(),
                            mesh=make_mesh([("dp", -1)]))
    x = nd.array(np.random.RandomState(0).rand(8, 64).astype("float32"))
    y = nd.array(np.arange(8) % 10)
    tr.step(x, y)
    donation = tm_memory.last_donation_report()
    log("donation report: %s" % (donation,))
    mem_phases["trainer_step"] = tm_memory.read_process_memory()

    try:
        sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))),
                             timeout=10).stdout.strip() or None
    except Exception:
        sha = None
    ok = bool(footprint and rejected and accepted and warn_accepted
              and donation and donation.get("ok"))
    result = {
        "mode": "serve_memory",
        "metric": "serve_memory_budget_mb%d" % args.max_batch,
        "footprint_bytes": footprint,
        "per_bucket_memory": per_bucket,
        "over_budget_rejected": rejected,
        "reject_status": reject_status,
        "warn_mode_accepted": warn_accepted,
        "within_budget_accepted": accepted,
        "donation": donation,
        "memory_phases": mem_phases,
        "executables_by_temp": tm_memory.executables_top(5),
        "ok": ok,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "device",
        "sha": sha,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0 if ok else 4


if __name__ == "__main__":
    sys.exit(main())
