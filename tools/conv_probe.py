"""Per-shape conv backward probe: measure fwd / dgrad / wgrad TFLOP/s for
the ResNet-50 conv shapes in NCHW vs NHWC dimension numbers on the real
chip, to find where backward MFU goes and whether logical layout matters.

Timing methodology: each measurement runs ITERS kernel executions inside a
single jitted `lax.fori_loop` whose carry feeds a numerically-negligible
scalar (scaled 1e-30; exact *0 would constant-fold) from each iteration's
output into one of the next iteration's operands. The data dependency
stops XLA from overlapping/hoisting iterations, so one wall-clock
measurement of the loop divides into per-iteration time. A free-running
Python loop (the previous version) measured only dispatch throughput over
the remote-PJRT tunnel and reported impossible TFLOP/s.

Which operand carries the chain matters:
- fwd / dgrad chain through the *weight* (tiny, free to perturb);
- wgrad's operands are the input and the cotangent, so the chain goes
  through a freshly-filled cotangent; the fill costs one HBM pass over
  the output, measured separately (`fill` loop) and subtracted.
"""
import json
import os
import time

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 400))

# (cin, cout, hw, k, stride) — representative ResNet-50 bulk shapes
SHAPES = [
    (3, 64, 224, 7, 2),     # stem
    (64, 64, 56, 3, 1),     # layer1 3x3
    (64, 256, 56, 1, 1),    # layer1 expand
    (128, 128, 28, 3, 1),   # layer2 3x3
    (256, 256, 14, 3, 1),   # layer3 3x3 (deepest bulk)
    (512, 512, 7, 3, 1),    # layer4 3x3
    (256, 512, 28, 1, 2),   # downsample 1x1/2
]


_RTT = None


def _rtt():
    """One dispatch+fetch round trip over the remote-PJRT tunnel. On axon,
    block_until_ready does not wait for remote execution — only fetching a
    value to host does — so every timing below fetches its carry scalar and
    subtracts this baseline."""
    global _RTT
    if _RTT is None:
        import jax
        import jax.numpy as jnp

        tiny = jax.jit(lambda v: v + 1.0)
        z = jnp.zeros((), jnp.float32)
        float(tiny(z))
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(tiny(z))
            samples.append(time.perf_counter() - t0)
        _RTT = min(samples)
        print(json.dumps({"rtt_ms": round(_RTT * 1e3, 3)}), flush=True)
    return _RTT


def _timed(loop, *args):
    float(loop(*args))  # compile + warm; fetch forces real completion
    t0 = time.perf_counter()
    float(loop(*args))
    dt = time.perf_counter() - t0
    return max(dt - _rtt(), 1e-9) / ITERS


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chain(val):
        # full reduce: every output element feeds the carry, so XLA cannot
        # narrow the producing kernel to a single-element slice (a [0]
        # element chain let the simplifier collapse each conv to one
        # output-pixel dot product). The reduce fuses into the kernel's
        # epilogue; *1e-30 keeps the perturbation numerically nil without
        # the exact-zero constant fold.
        return jnp.sum(val, dtype=jnp.float32) * 1e-30

    for (cin, cout, hw, k, s) in SHAPES:
        pad = k // 2
        ho = hw // s
        flops = 2 * BATCH * cout * ho * ho * cin * k * k
        row = {"cin": cin, "cout": cout, "hw": hw, "k": k, "s": s,
               "gflops": round(flops / 1e9, 1)}
        # weight specs mirror the framework's _conv_dnums (ops/nn.py):
        # NCHW carries OIHW weights, NHWC carries OHWI — probing the
        # exact dimension numbers the zoo's layout= path emits
        for layout, kspec in {"NCHW": "OIHW", "NHWC": "OHWI"}.items():
            dn = lax.conv_dimension_numbers(
                (1, 1, 1, 1), (1, 1, 1, 1), (layout, kspec, layout))
            if layout == "NCHW":
                xs = (BATCH, cin, hw, hw)
                os_ = (BATCH, cout, ho, ho)
                ws = (cout, cin, k, k)
            else:
                xs = (BATCH, hw, hw, cin)
                os_ = (BATCH, ho, ho, cout)
                ws = (cout, k, k, cin)
            x = jax.random.normal(jax.random.PRNGKey(0), xs,
                                  jnp.float32).astype(jnp.bfloat16)
            w = jax.random.normal(jax.random.PRNGKey(1), ws,
                                  jnp.float32).astype(jnp.bfloat16)

            def conv(xx, ww, dn=dn):
                return lax.conv_general_dilated(
                    xx, ww, window_strides=(s, s),
                    padding=[(pad, pad), (pad, pad)],
                    dimension_numbers=dn)

            @jax.jit
            def fwd_loop(x, w):
                def body(_, c):
                    return chain(conv(x, w + c.astype(w.dtype)))
                return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

            @jax.jit
            def dgrad_loop(x, w):
                # d/dx of sum(conv): cotangent is constant ones (hoisted);
                # the dgrad conv runs with the chained weight each iteration
                # and the unused forward conv is DCE'd — this times dgrad
                # alone.
                def body(_, c):
                    g = jax.grad(
                        lambda xx: conv(xx, w + c.astype(w.dtype))
                        .astype(jnp.float32).sum())(x)
                    return chain(g)
                return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

            @jax.jit
            def wgrad_loop(x, w):
                # wgrad contracts input with cotangent; the chain must ride
                # the cotangent (input is loop-invariant, weight is not an
                # operand). Fill cost measured by fill_loop and subtracted.
                def body(_, c):
                    ct = jnp.full(os_, 1, jnp.bfloat16) + c.astype(jnp.bfloat16)
                    _, pull = jax.vjp(lambda ww: conv(x, ww), w)
                    gw, = pull(ct)
                    return chain(gw)
                return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

            @jax.jit
            def fill_loop(x, w):
                def body(_, c):
                    ct = jnp.full(os_, 1, jnp.bfloat16) + c.astype(jnp.bfloat16)
                    return chain(ct)
                return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

            # dgrad REWRITE candidate (the VERDICT escalation path): XLA
            # lowers the autodiff dgrad as an lhs-dilated conv; this
            # variant materializes the zero-stuffing explicitly and runs a
            # PLAIN stride-1 conv over it. Only meaningful for s > 1 (at
            # s=1 the two are the same program). NCHW only (the rewrite
            # decision rides whichever layout wins the base measurements).
            dgrad_rw_loop = None
            if s > 1 and layout == "NCHW":
                ho_, wo_ = hw // s, hw // s

                def upsample(ct):
                    b_ = ct.shape[0]
                    z = jnp.zeros((b_, cout, ho_, s, wo_, s), ct.dtype)
                    z = z.at[:, :, :, 0, :, 0].set(ct)
                    return z.reshape(b_, cout, ho_ * s, wo_ * s)

                def dgrad_rewrite(ct, ww):
                    # dx = up(ct) (*) rot180(w)^T, stride 1. The
                    # zero-stuffed map has length Ho*s == H (trailing
                    # s-1 zeros included), so the plain conv needs
                    # lo = k-1-pad and hi = pad to land on exactly H:
                    # H + lo + hi - k + 1 = H.
                    w_rot = jnp.flip(ww, axis=(-1, -2)).transpose(
                        (1, 0, 2, 3))
                    lo = k - 1 - pad
                    return lax.conv_general_dilated(
                        upsample(ct), w_rot, (1, 1),
                        padding=[(lo, pad), (lo, pad)],
                        dimension_numbers=dn)

                # correctness gate at the real shape: the rewrite must
                # match the autodiff dgrad before its timing can count.
                # bf16 accumulation order differs between the two
                # programs, so the tolerance is RELATIVE to the output
                # magnitude (an absolute 1e-2 is below one bf16 ULP at
                # the stem's ~30-magnitude outputs and would spuriously
                # reject a correct rewrite)
                ct_probe = jax.random.normal(
                    jax.random.PRNGKey(2), os_, jnp.float32) \
                    .astype(jnp.bfloat16)
                ref_dx = jax.jit(lambda c: jax.vjp(
                    lambda xx: conv(xx, w), x)[1](c)[0])(ct_probe)
                got_dx = jax.jit(dgrad_rewrite)(ct_probe, w)
                diff = (ref_dx - got_dx).astype(jnp.float32)
                scale = float(jnp.max(jnp.abs(
                    ref_dx.astype(jnp.float32)))) or 1.0
                err = float(jnp.max(jnp.abs(diff))) / scale
                if err > 0.05:
                    row.setdefault("rewrite_error", {})[layout] = err
                else:
                    @jax.jit
                    def dgrad_rw_loop(x_, w_):
                        def body(_, c):
                            ct = jnp.full(os_, 1, jnp.bfloat16) \
                                + c.astype(jnp.bfloat16)
                            return chain(dgrad_rewrite(ct, w_))
                        return lax.fori_loop(0, ITERS, body,
                                             jnp.zeros((), jnp.float32))

            dt_f = _timed(fwd_loop, x, w)
            dt_d = _timed(dgrad_loop, x, w)
            dt_fill = _timed(fill_loop, x, w)
            dt_w = max(_timed(wgrad_loop, x, w) - dt_fill, 1e-9)
            row[layout] = {
                "fwd_tflops": round(flops / dt_f / 1e12, 1),
                "dgrad_tflops": round(flops / dt_d / 1e12, 1),
                "wgrad_tflops": round(flops / dt_w / 1e12, 1),
                "fwd_ms": round(dt_f * 1e3, 3),
                "dgrad_ms": round(dt_d * 1e3, 3),
                "wgrad_ms": round(dt_w * 1e3, 3),
                "fill_ms": round(dt_fill * 1e3, 3),
            }
            if dgrad_rw_loop is not None:
                dt_rw = max(_timed(dgrad_rw_loop, x, w) - dt_fill, 1e-9)
                row[layout]["dgrad_rewrite_ms"] = round(dt_rw * 1e3, 3)
                row[layout]["dgrad_rewrite_tflops"] = round(
                    flops / dt_rw / 1e12, 1)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
