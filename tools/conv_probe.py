"""Per-shape conv backward probe: measure fwd / dgrad / wgrad TFLOP/s for
the ResNet-50 conv shapes in NCHW vs NHWC dimension numbers on the real
chip, to find where backward MFU goes and whether logical layout matters.
"""
import json
import os
import time
from functools import partial

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 20))

# (cin, cout, hw, k, stride) — representative ResNet-50 bulk shapes
SHAPES = [
    (3, 64, 224, 7, 2),     # stem
    (64, 64, 56, 3, 1),     # layer1 3x3
    (64, 256, 56, 1, 1),    # layer1 expand
    (128, 128, 28, 3, 1),   # layer2 3x3
    (256, 256, 14, 3, 1),   # layer3 3x3 (deepest bulk)
    (512, 512, 7, 3, 1),    # layer4 3x3
    (256, 512, 28, 1, 2),   # downsample 1x1/2
]


def timed(fn, *args, n=ITERS):
    import jax
    jax.block_until_ready(fn(*args))
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    results = []
    for (cin, cout, hw, k, s) in SHAPES:
        pad = k // 2
        ho = hw // s
        flops = 2 * BATCH * cout * ho * ho * cin * k * k
        row = {"cin": cin, "cout": cout, "hw": hw, "k": k, "s": s,
               "gflops": round(flops / 1e9, 1)}
        for layout, (lhs_spec, out_spec) in {
                "NCHW": ("NCHW", "NCHW"), "NHWC": ("NHWC", "NHWC")}.items():
            dn = lax.conv_dimension_numbers(
                (1, 1, 1, 1), (1, 1, 1, 1), (lhs_spec, "OIHW", out_spec))
            if layout == "NCHW":
                xs = (BATCH, cin, hw, hw)
            else:
                xs = (BATCH, hw, hw, cin)
            key = jax.random.PRNGKey(0)
            x = jax.random.normal(key, xs, jnp.float32).astype(jnp.bfloat16)
            w = jax.random.normal(jax.random.PRNGKey(1), (cout, cin, k, k),
                                  jnp.float32).astype(jnp.bfloat16)

            def conv(xx, ww, dn=dn):
                return lax.conv_general_dilated(
                    xx, ww, window_strides=(s, s),
                    padding=[(pad, pad), (pad, pad)],
                    dimension_numbers=dn)

            fwd = jax.jit(conv)
            dt_f = timed(fwd, x, w)

            dgrad = jax.jit(jax.grad(
                lambda xx, ww: conv(xx, ww).astype(jnp.float32).sum(),
                argnums=0))
            dt_d = timed(dgrad, x, w)

            wgrad = jax.jit(jax.grad(
                lambda xx, ww: conv(xx, ww).astype(jnp.float32).sum(),
                argnums=1))
            dt_w = timed(wgrad, x, w)

            row[layout] = {
                "fwd_tflops": round(flops / dt_f / 1e12, 1),
                "dgrad_tflops": round(flops / dt_d / 1e12, 1),
                "wgrad_tflops": round(flops / dt_w / 1e12, 1),
            }
        results.append(row)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
