"""Distributed job launcher (reference: tools/launch.py — the dmlc-tracker
front-end that spawned scheduler/server/worker processes over
ssh/mpi/yarn/sge).

TPU-native: there are no parameter servers; every process is a worker in a
synchronous `jax.distributed` group (the coordinator service replaces the
ps-lite scheduler rendezvous — SURVEY §5.8). Launch modes:

  --launcher local   N processes on this host (the reference's nightly dist
                     tests pattern, tests/nightly/test_all.sh:55)
  --launcher ssh     one process per hostfile slot over ssh (reference
                     dmlc-tracker/ssh.py); requires -H/--hostfile with
                     `host` or `host:slots` lines; rank 0's host serves the
                     coordinator, so its address must be reachable from all
                     hosts
  --launcher mpi     delegates process placement to mpirun/mpiexec
                     (reference dmlc-tracker/mpi.py); ranks resolve via
                     OMPI_COMM_WORLD_RANK/PMI_RANK inside
                     `init_process_group`, so the command needs no wrapper

yarn/sge submission is a documented divergence: on TPU fleets the cluster
scheduler (k8s/slurm) owns placement, and `init_process_group` reads
SLURM_PROCID/SLURM_STEP_NUM_TASKS directly — `srun python train.py` on a
pod is the whole launch story (parallel/collectives.py:init_process_group).

Every mode emits the standard env protocol so
`mxnet_tpu.kv.create('dist_sync')` works unmodified:

  MXTPU_COORDINATOR          host:port of process 0's coordinator service
  MXTPU_NUM_WORKERS          group size        (alias: DMLC_NUM_WORKER)
  MXTPU_PROCESS_ID           this process rank (alias: DMLC_WORKER_ID)
  MXTPU_RESTART_GENERATION   supervised respawn count (0 = first launch)

Elastic supervision (--max-restarts N, docs/fault_tolerance.md): the
launcher supervises the group; the FIRST rank failure triggers an
escalating SIGTERM→SIGKILL teardown of every worker's process group (no
rank is ever left parked in a rendezvous waiting for a dead peer), then —
restarts permitting — the whole group respawns after an exponential
backoff on a FRESH rendezvous port. Workers resume from the last complete
checkpoint via parallel.resilience. Local/ssh worker output is prefixed
per rank so multi-rank post-mortems stay readable. This restores, in
TPU-native form, the node-failure semantics ps-lite's scheduler provided
the reference (PAPER §1 layer map).

Preemption (MXTPU_PREEMPT_EXIT_CODE, default 83): a worker that exits
with the graceful-preemption rc checkpointed on its way out (SIGTERM +
grace window, parallel.resilience.maybe_preempt_exit), so the launcher
restarts the group WITHOUT consuming the --max-restarts budget and with
the backoff reset to its initial value — preemptions are scheduler
events, not crash loops. A `preempt` launcher event records each one.

Usage:
  python tools/launch.py -n 4 python train.py ...
  python tools/launch.py -n 4 --max-restarts 3 python train.py ...
  python tools/launch.py -n 8 --launcher ssh -H hosts.txt python train.py ...
  python tools/launch.py -n 16 --launcher mpi --hostfile hosts.txt -- \
      python train.py ...
"""
from __future__ import annotations

import argparse
import os
import random
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _remote_port():
    """Coordinator port for a REMOTE rank-0 host. Nothing can be verified
    from here, so pick from a band below Linux's default ephemeral range
    (32768+) to minimise collision odds; pass --port to pin one that is
    known-free on the rank-0 host."""
    return random.randint(10000, 29999)


def _protocol_env(n, coord, extra, rank=None, generation=0):
    """The env-var protocol workers see. rank=None yields only the
    rank-independent half (mpi mode: the process manager assigns ranks).
    `generation` counts supervised group restarts (0 = first launch) so
    workers — and the MXTPU_FAULT_INJECT harness — can tell a respawned
    life from the original (parallel/resilience.py:restart_generation)."""
    env = {
        "MXTPU_COORDINATOR": coord,
        "MXTPU_NUM_WORKERS": str(n),
        "MXTPU_RESTART_GENERATION": str(generation),
        # distributed-tracing context: worker step spans join the launch
        # trace under this generation's span (telemetry/tracing.py; the
        # flags bit carries whether the launcher env samples the run)
        "MXTPU_TRACE_CONTEXT": _generation_trace_context(generation),
        # reference-compatible aliases (DMLC_* protocol, launch.py:29)
        "DMLC_NUM_WORKER": str(n),
        "DMLC_ROLE": "worker",
    }
    if rank is not None:
        env["MXTPU_PROCESS_ID"] = str(rank)
        env["DMLC_WORKER_ID"] = str(rank)
    for kv in extra:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def _parse_hostfile(path):
    """`host` or `host:slots` per line (dmlc hostfile format); '#' comments.
    Returns one host entry per slot: ["a", "a", "b", ...]."""
    slots = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            host, _, n = line.partition(":")
            slots.extend([host.strip()] * (int(n) if n else 1))
    return slots


def _log(msg):
    sys.stderr.write("[launcher] %s\n" % msg)
    sys.stderr.flush()


def _emit_event(kind, **fields):
    """Launcher-side telemetry: append one JSON event line to
    $MXTPU_TELEMETRY_DIR/launcher-events.jsonl (the same directory workers
    flush their telemetry into — docs/observability.md). Deliberately
    stdlib-only and import-free: the launcher must never pay (or depend on)
    a framework/jax import just to supervise processes."""
    directory = os.environ.get("MXTPU_TELEMETRY_DIR")
    if not directory:
        return
    try:
        import json

        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "launcher-events.jsonl"), "a") as f:
            f.write(json.dumps({
                "kind": "event", "ts": time.time(), "event": kind,
                "pid": os.getpid(), "fields": fields}) + "\n")
    except OSError:
        pass  # telemetry must never break supervision


# -- launch trace (distributed tracing, docs/observability.md §Tracing) ----
# one trace id per launcher invocation; each supervised generation is a
# span under it, exported to workers via MXTPU_TRACE_CONTEXT so their
# training-step spans share the trace. Import-free like _emit_event: the
# launcher hand-rolls the same `{"kind": "event", "event": "span"}` record
# shape tools/trace_merge.py normalizes.
_LAUNCH_TRACE = "%032x" % random.getrandbits(128)
_GEN_SPANS = {}  # generation -> (span_id, start_wall)


def _launch_sampled():
    """Whether the launcher environment samples the run (workers inherit
    the flag and force-record their step spans when it is set)."""
    try:
        return float(os.environ.get("MXTPU_TRACE_SAMPLE") or 0) >= 1.0
    except ValueError:
        return False


def _generation_trace_context(generation):
    span_id, _ = _GEN_SPANS.get(generation) or (None, None)
    if span_id is None:
        span_id = "%016x" % random.getrandbits(64)
        _GEN_SPANS[generation] = (span_id, time.time())
    return "%s-%s-%02d" % (_LAUNCH_TRACE, span_id,
                           1 if _launch_sampled() else 0)


def _emit_generation_span(generation, rc):
    """Close generation `generation`'s span (emitted at exit, when its
    duration is known) into launcher-events.jsonl."""
    span_id, start = _GEN_SPANS.get(generation) or (None, None)
    if span_id is None:
        return
    _emit_event("span", name="launch.generation", trace=_LAUNCH_TRACE,
                span=span_id, parent=None, component="launcher",
                ts=start, dur_us=(time.time() - start) * 1e6,
                attrs={"generation": generation, "rc": rc})


_PUMP_LOCK = threading.Lock()


def _pump(stream, label):
    """Copy one worker's merged stdout/stderr to our stdout, prefixing every
    line with its rank — post-mortems of a multi-rank failure stay readable
    (the reference dmlc-tracker interleaved raw streams)."""
    out = sys.stdout.buffer if hasattr(sys.stdout, "buffer") else None
    prefix = ("[%s] " % label).encode()
    for line in iter(stream.readline, b""):
        with _PUMP_LOCK:
            if out is not None:
                out.write(prefix + line)
                out.flush()
            else:  # stdout replaced by a text-only object (capture shims)
                sys.stdout.write((prefix + line).decode("utf-8", "replace"))
                sys.stdout.flush()
    stream.close()


def _signal_group(procs, sig):
    """Deliver `sig` to each worker's whole process GROUP (workers are
    spawned session leaders), so grandchildren — dataloader workers, shells
    the command spawned — die with it instead of leaking."""
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(p.pid, sig)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    p.send_signal(sig)
                except OSError:
                    pass


def _teardown(procs, grace=None, generation=None):
    """Escalating group teardown: when MXTPU_TELEMETRY_DIR is configured,
    SIGUSR1 first (flight-recorder dump — every survivor writes thread
    stacks + recent telemetry events before dying, so a hung worker's
    teardown always leaves a diagnosis behind, telemetry/recorder.py);
    then SIGTERM, give the group `grace` seconds (MXTPU_TEARDOWN_GRACE,
    default 10) to exit cleanly — flushing logs, closing checkpoints in
    progress — then SIGKILL the survivors. A rank wedged in a collective
    waiting for the dead peer ignores nothing after SIGKILL, so the
    restart loop is never blocked by a hung group."""
    if all(p.poll() is not None for p in procs):
        return
    if grace is None:
        grace = float(os.environ.get("MXTPU_TEARDOWN_GRACE", "10"))
    survivors = [p for p in procs if p.poll() is None]
    # SIGUSR1 only when telemetry output is configured: mxnet_tpu installs
    # the dump handler at import under MXTPU_TELEMETRY_DIR, so every
    # framework worker dumps-and-survives. Without the dir (or for
    # non-framework commands) SIGUSR1's DEFAULT action would terminate the
    # worker instantly, robbing it of its SIGTERM cleanup grace — so the
    # launcher skips the broadcast rather than break teardown semantics.
    dump_first = hasattr(signal, "SIGUSR1") and \
        bool(os.environ.get("MXTPU_TELEMETRY_DIR"))
    _log("tearing down %d live worker(s): %sSIGTERM, SIGKILL after %.0fs"
         % (len(survivors),
            "SIGUSR1 (flight-recorder dump), then " if dump_first else "",
            grace))
    _emit_event("launcher_teardown", live=len(survivors), grace_s=grace,
                dump_first=dump_first, generation=generation)
    if dump_first:
        _signal_group(procs, signal.SIGUSR1)
        # let handlers write their dump files before SIGTERM lands
        dump_grace = float(os.environ.get("MXTPU_DUMP_GRACE", "1.0"))
        deadline = time.time() + dump_grace
        while time.time() < deadline and any(p.poll() is None for p in procs):
            time.sleep(0.05)
    _signal_group(procs, signal.SIGTERM)
    deadline = time.time() + grace
    while time.time() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.05)
    survivors = [p for p in procs if p.poll() is None]
    if survivors:
        _log("%d worker(s) survived SIGTERM for %.0fs; sending SIGKILL"
             % (len(survivors), grace))
        _signal_group(survivors, signal.SIGKILL)
    for p in procs:
        try:
            p.wait(timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            pass


def _preempt_exit_code():
    """The graceful-preemption rc contract (parallel/resilience.py
    maybe_preempt_exit), read import-free from the env like the rest of
    the launcher."""
    try:
        return int(os.environ.get("MXTPU_PREEMPT_EXIT_CODE", "83"))
    except ValueError:
        return 83


def _run_generation(cmds, preempt_rc=None, generation=None):
    """Spawn every (argv, env, label) and supervise by polling: the FIRST
    failure — a spawn error partway through the list, or any worker exiting
    nonzero — tears the survivors down (escalating SIGTERM→SIGKILL on the
    process groups), so one crashed rank never leaves the rest parked in
    the rendezvous waiting for it. Workers that exit 0 simply leave the
    others to finish. (ssh mode: the teardown hits the local ssh client;
    sshd tears the remote command down with the connection.) Labeled
    workers get their output line-prefixed via a pump thread.

    Returns (rc, preempted). `preempted` is True when ANY worker's final
    rc equals `preempt_rc` — checked after teardown, because the
    first-OBSERVED exit may be a peer's collective error while the
    actually-preempted rank (which DID land an emergency checkpoint
    before exiting) finished an instant earlier. Also counts a worker
    that preempt-exits gracefully under the teardown SIGTERM itself:
    either way a fresh checkpoint exists, so the restart makes progress."""
    procs, pumps = [], []
    rc = 0
    try:
        for argv, env, label in cmds:
            p = subprocess.Popen(
                argv, env=env, start_new_session=True,
                stdout=subprocess.PIPE if label else None,
                stderr=subprocess.STDOUT if label else None)
            procs.append(p)
            if label:
                t = threading.Thread(target=_pump, args=(p.stdout, label),
                                     daemon=True)
                t.start()
                pumps.append(t)
        pending = list(procs)
        while pending and not rc:
            for p in list(pending):
                r = p.poll()
                if r is not None:
                    pending.remove(p)
                    rc = rc or r
            if pending and not rc:
                time.sleep(0.1)
    finally:
        _teardown(procs, generation=generation)  # nonzero rc -> stragglers
        for t in pumps:
            t.join(timeout=5)
    preempted = preempt_rc is not None and any(
        p.returncode == preempt_rc for p in procs)
    return rc, preempted


def _spawn_and_wait(make_cmds, max_restarts=0, backoff=1.0):
    """Supervising restart loop (the elastic-training front half; the back
    half is checkpoint auto-resume, parallel/resilience.py). `make_cmds`
    maps a generation number to the (argv, env, label) list for that
    generation — called FRESH each time so every generation gets a new
    rendezvous port (the dead coordinator's port may sit in TIME_WAIT) and
    workers see MXTPU_RESTART_GENERATION. On group failure: escalating
    teardown, exponential-backoff wait, respawn — up to `max_restarts`
    times, after which the last exit code propagates.

    Two exits are NOT ordinary failures: a generation where some worker
    exited with the graceful-preemption rc (MXTPU_PREEMPT_EXIT_CODE,
    default 83) restarts for FREE — the preempted rank checkpointed on
    its way out, so the retry makes forward progress and should not
    burn the crash budget — and the backoff ramp resets to its initial
    value, since exponential backoff exists to damp crash loops, not to
    punish schedulers for reclaiming capacity."""
    generation = 0
    restarts_used = 0
    initial_delay = max(backoff, 0.0)
    delay = initial_delay
    prev_exit = None  # (ts, rc, preempted) of the previous generation
    while True:
        if generation:
            _log("spawning generation %d" % generation)
        if prev_exit is not None:
            # goodput job ledger (docs/observability.md §Goodput): the gap
            # between the previous generation's teardown and this spawn is
            # categorized downtime — labeled preempt vs crash from the
            # rc-83 contract. tools/goodput_report.py joins it (plus each
            # rank's goodput_first_step event for the restore→first-step
            # tail) against per-rank phase totals.
            _emit_event("launcher_downtime", generation=generation,
                        cause="preempt" if prev_exit[2] else "crash",
                        rc=prev_exit[1],
                        down_s=round(time.time() - prev_exit[0], 3))
        _emit_event("launcher_generation_start", generation=generation,
                    max_restarts=max_restarts)
        rc, preempted = _run_generation(make_cmds(generation),
                                        _preempt_exit_code(),
                                        generation=generation)
        prev_exit = (time.time(), rc, preempted)
        _emit_event("launcher_generation_exit", generation=generation, rc=rc,
                    preempted=preempted)
        _emit_generation_span(generation, rc)
        if rc == 0:
            return 0
        if preempted and max_restarts > 0:
            # free restart: the preempted rank landed an emergency
            # checkpoint before exiting, so the next generation resumes
            # with fresh progress — budget untouched, backoff reset
            generation += 1
            delay = initial_delay
            _log("group preempted (rc=%d); free restart as generation %d in "
                 "%.1fs (restart budget untouched: %d/%d used)"
                 % (rc, generation, delay, restarts_used, max_restarts))
            _emit_event("preempt", generation=generation, rc=rc,
                        restarts_used=restarts_used, backoff_s=delay)
            if delay:
                time.sleep(delay)
            continue
        if restarts_used >= max_restarts:
            if max_restarts:
                _log("group failed (rc=%d); %d restart(s) exhausted, giving "
                     "up" % (rc, max_restarts))
            _emit_event("launcher_restarts_exhausted", generation=generation,
                        rc=rc)
            return rc
        generation += 1
        restarts_used += 1
        _log("group failed (rc=%d); restarting (%d/%d) in %.1fs on a fresh "
             "rendezvous port" % (rc, restarts_used, max_restarts, delay))
        _emit_event("launcher_restart", generation=generation, rc=rc,
                    backoff_s=delay)
        if delay:
            time.sleep(delay)
        delay = min(max(delay, 0.5) * 2, 60.0)


def _launch_local(args):
    def make_cmds(generation):
        # fresh port per generation: --port pins one (the old coordinator is
        # dead by restart time, so rebinding it is safe), else probe anew
        port = args.port or _free_port()
        coord = "127.0.0.1:%d" % port
        cmds = []
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env.update(_protocol_env(args.num_workers, coord, args.env, rank,
                                     generation))
            cmds.append((args.command, env, "rank %d" % rank))
        return cmds

    return _spawn_and_wait(make_cmds, args.max_restarts, args.restart_backoff)


def _launch_ssh(args):
    """One ssh session per rank (reference dmlc-tracker/ssh.py): env rides
    inline `env K=V` prefixes because sshd filters most SendEnv vars, and
    the remote cwd mirrors the local one (the dmlc assumption: a shared
    filesystem or identical checkouts)."""
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires -H/--hostfile")
    slots = _parse_hostfile(args.hostfile)
    if len(slots) < args.num_workers:
        raise SystemExit("hostfile provides %d slots < -n %d"
                         % (len(slots), args.num_workers))
    cwd = os.getcwd()
    ssh = shlex.split(args.ssh_cmd)

    def make_cmds(generation):
        port = args.port or _remote_port()
        coord = "%s:%d" % (slots[0], port)
        cmds = []
        for rank in range(args.num_workers):
            host = slots[rank]
            env = _protocol_env(args.num_workers, coord, args.env, rank,
                                generation)
            # PYTHONPATH travels so `python tools/launch.py` from a checkout
            # works without install on the remote side
            if os.environ.get("PYTHONPATH"):
                env.setdefault("PYTHONPATH", os.environ["PYTHONPATH"])
            envs = " ".join("%s=%s" % (k, shlex.quote(v))
                            for k, v in sorted(env.items()))
            remote = "cd %s && env %s %s" % (
                shlex.quote(cwd), envs,
                " ".join(shlex.quote(c) for c in args.command))
            cmds.append((ssh + [host, remote], dict(os.environ),
                         "rank %d" % rank))
        return cmds

    return _spawn_and_wait(make_cmds, args.max_restarts, args.restart_backoff)


# per-flavor syntax for exporting one env var through the mpi launcher
_MPI_ENV_FLAG = {
    "openmpi": lambda k, v: ["-x", k],          # value from mpirun's env
    "mpich": lambda k, v: ["-genv", k, v],      # mpiexec/hydra, Intel MPI
    "none": lambda k, v: [],                    # cluster forwards env itself
}


def _launch_mpi(args):
    """Delegate placement to mpirun (reference dmlc-tracker/mpi.py). Rank
    and size are NOT passed per-process — `init_process_group` reads
    OMPI_COMM_WORLD_RANK/PMI_RANK in each worker, so one mpirun command
    covers every rank. The coordinator is bound by worker rank 0, so its
    default address follows the placement: the hostfile's first host when
    one is given (mpirun fills hosts in order), else this host (purely
    local mpirun). --coordinator-host/--port override both."""
    def make_cmds(generation):
        if args.coordinator_host:
            host = args.coordinator_host
            port = args.port or _remote_port()
        elif args.hostfile:
            host = _parse_hostfile(args.hostfile)[0]
            # rank 0 is remote: no local probe can verify its ports
            port = args.port or _remote_port()
        else:
            host = "127.0.0.1"
            port = args.port or _free_port()
        coord = "%s:%d" % (host, port)
        proto = _protocol_env(args.num_workers, coord, args.env,
                              generation=generation)
        env = dict(os.environ)
        env.update(proto)
        cmd = shlex.split(args.mpi_cmd) + ["-np", str(args.num_workers)]
        if args.hostfile:
            cmd += ["--hostfile", args.hostfile]
        flag = _MPI_ENV_FLAG[args.mpi_flavor]
        export = set(proto)
        if "PYTHONPATH" in env:
            export.add("PYTHONPATH")
        for var in sorted(export):
            cmd += flag(var, env[var])
        # label=None: mpirun already multiplexes rank output; piping it
        # through a prefix pump would only obscure mpirun's own framing
        return [(cmd + args.command, env, None)]

    return _spawn_and_wait(make_cmds, args.max_restarts, args.restart_backoff)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (local/ssh/mpi)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi"],
                        help="process placement: local spawns on this host; "
                             "ssh uses -H/--hostfile; mpi delegates to "
                             "mpirun (yarn/sge: use your cluster scheduler "
                             "— see module doc)")
    parser.add_argument("-H", "--hostfile",
                        help="hosts, one `host` or `host:slots` per line "
                             "(ssh: required; mpi: forwarded to mpirun)")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (default: a free local port "
                             "for local/mpi; a random 10000-29999 port for "
                             "ssh, where rank 0 is remote and can't be "
                             "probed — pin this if it might collide)")
    parser.add_argument("--coordinator-host", default=None,
                        help="mpi: address workers dial for rank-0 "
                             "rendezvous (default: this host's fqdn)")
    parser.add_argument("--ssh-cmd", default="ssh -o StrictHostKeyChecking=no",
                        help="ssh client command (tests substitute a local "
                             "shim)")
    parser.add_argument("--mpi-cmd", default="mpirun",
                        help="mpi launcher command (tests substitute a "
                             "local shim)")
    parser.add_argument("--mpi-flavor", default="openmpi",
                        choices=sorted(_MPI_ENV_FLAG),
                        help="env-export syntax: openmpi uses `-x VAR`, "
                             "mpich/Intel uses `-genv VAR VAL`, none skips "
                             "env flags (scheduler forwards the env)")
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VAL for every worker")
    parser.add_argument("--compile-cache", nargs="?", const="1",
                        default=None, metavar="DIR",
                        help="arm the persistent executable-artifact tier "
                             "(MXTPU_COMPILE_CACHE, docs/compile_cache.md) "
                             "for every worker in every generation: a "
                             "restarted generation reloads its compiled "
                             "steps from DIR (default: the repo-local "
                             "cache) and reaches step 1 with zero "
                             "jit_compile events")
    parser.add_argument("--sharded-step", action="store_true",
                        help="export MXTPU_SHARDED_STEP=1 fleet-wide: "
                             "gluon.Trainer(block=)/module.fit() promote "
                             "to the fused whole-step executable "
                             "(docs/sharded_training.md); pair with "
                             "--compile-cache so restarts skip compiles")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="elastic supervision: after a group failure "
                             "(escalating SIGTERM→SIGKILL teardown) respawn "
                             "the whole group up to N times with exponential "
                             "backoff and a fresh rendezvous port; workers "
                             "see MXTPU_RESTART_GENERATION and auto-resume "
                             "from the last complete checkpoint "
                             "(parallel/resilience.py). Graceful preemptions "
                             "(exit rc MXTPU_PREEMPT_EXIT_CODE, default 83) "
                             "restart for free — they do not consume this "
                             "budget. Default 0 = fail fast, the pre-elastic "
                             "behavior")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="initial seconds between generations (doubles "
                             "each restart, capped at 60; resets to the "
                             "initial value after a graceful preemption)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        parser.error("no command given")
    # restart-path arming: fold the cache/promotion flags into the --env
    # list so every launcher AND every elastic restart generation
    # (_protocol_env) exports them — explicit --env KEY=VAL still wins
    # because later entries overwrite earlier ones
    armed = []
    if args.compile_cache is not None:
        armed.append("MXTPU_COMPILE_CACHE=%s" % args.compile_cache)
    if args.sharded_step:
        armed.append("MXTPU_SHARDED_STEP=1")
    if armed:
        args.env = armed + args.env

    return {"local": _launch_local,
            "ssh": _launch_ssh,
            "mpi": _launch_mpi}[args.launcher](args)


if __name__ == "__main__":
    sys.exit(main())
