"""Distributed job launcher (reference: tools/launch.py — the dmlc-tracker
front-end that spawned scheduler/server/worker processes over ssh/mpi/yarn).

TPU-native: there are no parameter servers; every process is a worker in a
synchronous `jax.distributed` group (the coordinator service replaces the
ps-lite scheduler rendezvous — SURVEY §5.8). This launcher covers the
`local` cluster type (N processes on this host — the reference's nightly
dist tests pattern, tests/nightly/test_all.sh:55) and emits the standard
env-var protocol so `mxnet_tpu.kv.create('dist_sync')` works unmodified:

  MXTPU_COORDINATOR     host:port of process 0's coordinator service
  MXTPU_NUM_WORKERS     group size        (alias: DMLC_NUM_WORKER)
  MXTPU_PROCESS_ID      this process rank (alias: DMLC_WORKER_ID)

For multi-host, run the same command on each host with MXTPU_PROCESS_ID
set per host and MXTPU_COORDINATOR pointing at host 0 (ssh/mpi orchestration
is left to the cluster scheduler — slurm/k8s do what dmlc-tracker did).

Usage: python tools/launch.py -n 4 [--port 52321] python train.py ...
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (local cluster)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("--launcher", default="local",
                        choices=["local"],
                        help="only 'local' is built in; use your cluster "
                             "scheduler for multi-host (see module doc)")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VAL for every worker")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        parser.error("no command given")

    port = args.port or _free_port()
    coord = "127.0.0.1:%d" % port
    procs = []
    try:
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env["MXTPU_COORDINATOR"] = coord
            env["MXTPU_NUM_WORKERS"] = str(args.num_workers)
            env["MXTPU_PROCESS_ID"] = str(rank)
            # reference-compatible aliases (DMLC_* protocol, launch.py:29)
            env["DMLC_NUM_WORKER"] = str(args.num_workers)
            env["DMLC_WORKER_ID"] = str(rank)
            env["DMLC_ROLE"] = "worker"
            for kv in args.env:
                k, _, v = kv.partition("=")
                env[k] = v
            procs.append(subprocess.Popen(args.command, env=env))
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


if __name__ == "__main__":
    sys.exit(main())
