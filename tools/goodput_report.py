#!/usr/bin/env python
"""Whole-job goodput report: join the launcher's generation/downtime ledger
(`launcher-events.jsonl`) with each rank's per-phase goodput totals (the
final `telemetry-rank*-pid*.jsonl` metrics snapshot per process) into a
per-generation, per-phase decomposition of where a multi-restart training
job's wall-clock went (docs/observability.md §Goodput).

Stdlib-only (like tools/launch.py): the report must run on a machine with
nothing but the JSONL artifacts.

For every generation the launcher supervised:

  * wall        — launcher_generation_start → launcher_generation_exit
  * spawn       — generation start → worker process import (per rank)
  * startup     — import → first training step start (rendezvous, restore,
                  warmup; from the worker's `goodput_first_step` event)
  * phases      — the worker's cumulative `mxtpu_goodput_phase_seconds_total`
                  counters (data_wait / host_dispatch / compile / compute /
                  checkpoint_stall / collective / other / between_steps) —
                  a contiguous attribution of first-step-start → last-step-end
  * shutdown    — final telemetry flush → teardown start (or generation
                  exit when the generation ended cleanly without a
                  launcher teardown): interpreter epilogue per rank
  * teardown    — `launcher_teardown` → generation exit, generation-wide:
                  the SIGTERM→SIGKILL escalation window where survivors may
                  be wedged (e.g. an allreduce on a dead peer) and can no
                  longer account for themselves
  * trailer     — attributed window end → final telemetry flush (epilogue
                  inside the worker) — reported but NOT counted toward
                  coverage, so a broken attributor (attributed collapses,
                  trailer balloons) still fails `--check`

plus the labeled `launcher_downtime` gap BEFORE the generation
(teardown → respawn, cause preempt|crash from the rc-83 contract).

Coverage per rank = (spawn + startup + attributed + shutdown + teardown)
/ wall, capped at 1. `--check` fails (exit 1) unless every generation's
coverage is at least `--min-coverage` (default 0.9) and every restart that
followed a preemption carries a preempt-labeled downtime event.

Usage:
  python tools/goodput_report.py --dir /path/to/telemetry [--json] \
      [--check] [--min-coverage 0.9]
"""
import argparse
import glob
import json
import os
import re
import sys

PHASES = ("data_wait", "host_dispatch", "compile", "compute",
          "checkpoint_stall", "collective", "other", "between_steps")

_PHASE_RE = re.compile(
    r'^mxtpu_goodput_phase_seconds_total\{phase="([a-z_]+)"\}$')
_RANK_RE = re.compile(r"telemetry-rank(\d+)-pid(\d+)\.jsonl$")


def _read_jsonl(path):
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn final line of a killed process
    except OSError:
        pass
    return out


def load_launcher(directory):
    """Generation ledger from launcher-events.jsonl:
    {gen: {start, exit, rc, preempted, downtime: {cause, down_s, rc}}}."""
    gens = {}
    for rec in _read_jsonl(os.path.join(directory, "launcher-events.jsonl")):
        if rec.get("kind") != "event":
            continue
        ev, ts = rec.get("event"), rec.get("ts")
        f = rec.get("fields") or {}
        g = f.get("generation")
        if g is None:
            continue
        entry = gens.setdefault(g, {})
        if ev == "launcher_generation_start":
            entry["start"] = ts
        elif ev == "launcher_generation_exit":
            entry["exit"] = ts
            entry["rc"] = f.get("rc")
            entry["preempted"] = bool(f.get("preempted"))
        elif ev == "launcher_teardown":
            # clean generations emit no teardown event — missing means 0
            entry["teardown"] = ts
        elif ev == "launcher_downtime":
            entry["downtime"] = {"cause": f.get("cause"),
                                 "down_s": f.get("down_s"),
                                 "rc": f.get("rc")}
    return gens


def load_ranks(directory):
    """Per-(generation, rank) goodput totals from each worker's telemetry
    JSONL. One process == one generation, so the LAST metrics snapshot in
    a file is that generation's cumulative total."""
    out = {}  # (gen, rank) -> record
    for path in sorted(glob.glob(
            os.path.join(directory, "telemetry-rank*-pid*.jsonl"))):
        m = _RANK_RE.search(path)
        if not m:
            continue
        rank = int(m.group(1))
        last_metrics = None
        first_step = None
        for rec in _read_jsonl(path):
            if rec.get("kind") == "metrics":
                last_metrics = rec
            elif rec.get("kind") == "event" and \
                    rec.get("event") == "goodput_first_step":
                first_step = rec
        if last_metrics is None:
            continue
        gen = last_metrics.get("generation") or 0
        phases = {}
        wall_steps = 0.0
        for key, snap in (last_metrics.get("metrics") or {}).items():
            pm = _PHASE_RE.match(key)
            if pm:
                phases[pm.group(1)] = float(snap.get("value") or 0.0)
            elif key == "mxtpu_goodput_wall_seconds_total":
                wall_steps = float(snap.get("value") or 0.0)
        rec = {"rank": rank, "generation": gen, "path": path,
               "phases": phases, "step_wall_s": wall_steps,
               "final_flush_ts": last_metrics.get("ts")}
        if first_step is not None:
            f = first_step.get("fields") or {}
            rec["startup_s"] = float(f.get("startup_s") or 0.0)
            # attributed window starts at first step start
            rec["attr_start_ts"] = (first_step.get("ts") or 0.0) \
                - float(f.get("step_wall_s") or 0.0)
        prev = out.get((gen, rank))
        # a rank restarted within one launcher generation keeps the
        # freshest file (later final flush wins)
        if prev is None or (rec["final_flush_ts"] or 0) >= \
                (prev["final_flush_ts"] or 0):
            out[(gen, rank)] = rec
    return out


def build_report(directory, min_coverage=0.9):
    gens = load_launcher(directory)
    ranks = load_ranks(directory)
    report = {"directory": directory, "generations": [], "problems": []}
    if not gens:
        report["problems"].append("no launcher-events.jsonl generations "
                                  "found in %s" % directory)
        return report

    job_start = min(e["start"] for e in gens.values() if "start" in e)
    job_end = max(e.get("exit", e.get("start", 0)) for e in gens.values())
    total_compute = total_wall = total_down = 0.0

    for g in sorted(gens):
        entry = gens[g]
        start, end = entry.get("start"), entry.get("exit")
        wall = (end - start) if (start is not None and end is not None) \
            else None
        teardown_ts = entry.get("teardown")
        teardown_s = max(0.0, end - teardown_ts) \
            if (teardown_ts is not None and end is not None) else 0.0
        gen_ranks = sorted((rec for (gg, _), rec in ranks.items()
                            if gg == g), key=lambda r: r["rank"])
        agg = {p: 0.0 for p in PHASES}
        rank_rows = []
        coverages = []
        for rec in gen_ranks:
            attributed = sum(rec["phases"].values())
            row = {"rank": rec["rank"],
                   "phases": {p: round(v, 4)
                              for p, v in sorted(rec["phases"].items())},
                   "attributed_s": round(attributed, 4)}
            for p, v in rec["phases"].items():
                if p in agg:
                    agg[p] += v
            segments = attributed
            if "startup_s" in rec:
                row["startup_s"] = round(rec["startup_s"], 3)
                segments += rec["startup_s"]
            if "attr_start_ts" in rec and start is not None:
                spawn = max(0.0, (rec["attr_start_ts"]
                                  - rec.get("startup_s", 0.0)) - start)
                row["spawn_s"] = round(spawn, 3)
                segments += spawn
            if rec.get("final_flush_ts") and "attr_start_ts" in rec:
                trailer = max(0.0, (rec["final_flush_ts"]
                                    - rec["attr_start_ts"]) - attributed)
                row["trailer_s"] = round(trailer, 3)
            if rec.get("final_flush_ts"):
                # final flush -> teardown start (or clean exit): the
                # interpreter epilogue the worker can't see; a rank whose
                # final flush came DURING teardown clamps to 0 (that span
                # is already priced in teardown_s)
                shut_end = teardown_ts if teardown_ts is not None else end
                if shut_end is not None:
                    shutdown = max(0.0, shut_end - rec["final_flush_ts"])
                    row["shutdown_s"] = round(shutdown, 3)
                    segments += shutdown
            segments += teardown_s
            if wall:
                cov = min(1.0, segments / wall)
                row["coverage"] = round(cov, 4)
                coverages.append(cov)
            rank_rows.append(row)

        n = max(1, len(gen_ranks))
        compute = agg.get("compute", 0.0) / n
        mean_phases = {p: round(v / n, 4) for p, v in agg.items() if v}
        gen_row = {
            "generation": g,
            "wall_s": round(wall, 3) if wall is not None else None,
            "rc": entry.get("rc"),
            "preempted": entry.get("preempted", False),
            "ranks": rank_rows,
            "mean_phases_s": mean_phases,
            "mean_compute_s": round(compute, 4),
            "goodput_fraction": round(compute / wall, 4)
            if wall else None,
            "coverage": round(min(coverages), 4) if coverages else None,
        }
        if teardown_s:
            gen_row["teardown_s"] = round(teardown_s, 3)
        if "downtime" in entry:
            gen_row["downtime_before"] = entry["downtime"]
            total_down += entry["downtime"].get("down_s") or 0.0
        report["generations"].append(gen_row)
        if wall:
            total_wall += wall
            total_compute += compute

        # -- checks -------------------------------------------------------
        if wall is None:
            report["problems"].append(
                "generation %d has no start/exit pair (run still live, or "
                "a torn ledger)" % g)
        elif not gen_ranks:
            report["problems"].append(
                "generation %d: no rank telemetry found" % g)
        elif coverages and min(coverages) < min_coverage:
            report["problems"].append(
                "generation %d: attributed coverage %.1f%% < %.0f%% of "
                "wall" % (g, 100 * min(coverages), 100 * min_coverage))
        if g > 0:
            prev = gens.get(g - 1, {})
            dt = entry.get("downtime")
            if dt is None:
                report["problems"].append(
                    "generation %d: restart without a launcher_downtime "
                    "event" % g)
            elif prev.get("preempted") and dt.get("cause") != "preempt":
                report["problems"].append(
                    "generation %d followed a preemption but downtime is "
                    "labeled %r" % (g, dt.get("cause")))

    job_wall = job_end - job_start if job_end and job_start else None
    report["job"] = {
        "generations": len(gens),
        "wall_s": round(job_wall, 3) if job_wall else None,
        "generation_wall_s": round(total_wall, 3),
        "downtime_s": round(total_down, 3),
        "mean_compute_s": round(total_compute, 4),
        "goodput_fraction": round(total_compute / job_wall, 4)
        if job_wall else None,
    }
    return report


def render_text(report):
    lines = ["goodput report: %s" % report["directory"]]
    for g in report["generations"]:
        head = ("gen %d  wall=%ss rc=%s%s  goodput=%s coverage=%s"
                % (g["generation"], g["wall_s"], g["rc"],
                   " PREEMPTED" if g["preempted"] else "",
                   g["goodput_fraction"], g["coverage"]))
        if "teardown_s" in g:
            head += " teardown=%.3fs" % g["teardown_s"]
        lines.append(head)
        if "downtime_before" in g:
            d = g["downtime_before"]
            lines.append("  downtime before: %.3fs cause=%s rc=%s"
                         % (d.get("down_s") or 0.0, d.get("cause"),
                            d.get("rc")))
        if g["mean_phases_s"]:
            lines.append("  phases (mean/rank): " + "  ".join(
                "%s=%.3fs" % (p, v)
                for p, v in sorted(g["mean_phases_s"].items(),
                                   key=lambda kv: -kv[1])))
        for r in g["ranks"]:
            seg = ["rank %d:" % r["rank"]]
            for k in ("spawn_s", "startup_s", "attributed_s", "shutdown_s",
                      "trailer_s"):
                if k in r:
                    seg.append("%s=%.3f" % (k[:-2], r[k]))
            if "coverage" in r:
                seg.append("coverage=%.1f%%" % (100 * r["coverage"]))
            lines.append("  " + " ".join(seg))
    j = report.get("job") or {}
    lines.append("job: %d generation(s) wall=%ss downtime=%ss goodput=%s"
                 % (j.get("generations", 0), j.get("wall_s"),
                    j.get("downtime_s"), j.get("goodput_fraction")))
    for p in report["problems"]:
        lines.append("PROBLEM: %s" % p)
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.environ.get("MXTPU_TELEMETRY_DIR"),
                    help="telemetry directory (default: "
                         "$MXTPU_TELEMETRY_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of text")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every generation decomposes to "
                         ">= --min-coverage of wall and preempt downtime "
                         "is labeled")
    ap.add_argument("--min-coverage", type=float, default=0.9,
                    help="minimum attributed fraction of generation wall "
                         "(default 0.9)")
    args = ap.parse_args(argv)
    if not args.dir:
        ap.error("--dir (or MXTPU_TELEMETRY_DIR) is required")
    report = build_report(args.dir, min_coverage=args.min_coverage)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render_text(report))
    if args.check and report["problems"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
