#!/usr/bin/env python
"""Environment diagnostic (reference: tools/diagnose.py — platform/python/
dependency report for bug filing). TPU-native version adds the accelerator
dial check: the single most common failure here is a wedged remote-PJRT
tunnel, which hangs the first jax computation — probed in a subprocess
under a timeout so this script always terminates."""
from __future__ import annotations

import os
import platform
import subprocess
import sys
import time


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("cores        :", os.cpu_count())


def check_deps():
    print("----------Dependencies---------")
    for mod in ("numpy", "jax", "jaxlib", "flax", "optax", "orbax",
                "torch", "PIL"):
        try:
            m = __import__(mod)
            print("%-12s : %s" % (mod, getattr(m, "__version__", "present")))
        except Exception as e:
            print("%-12s : MISSING (%s)" % (mod, e))


def check_mxnet_tpu(timeout=120):
    """Probed in a CPU-pinned subprocess: feature detection runs jax
    computations, and in-process they would dial the accelerator tunnel."""
    print("----------mxnet_tpu------------")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=root + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import os, mxnet_tpu as mx\n"
        "print('ok', os.path.dirname(mx.__file__))\n"
        "from mxnet_tpu.runtime import feature_list\n"
        "print(', '.join('%s=%d' % (f.name, f.enabled)"
        " for f in feature_list()))\n")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout, env=env)
        lines = out.stdout.strip().splitlines()
        if out.returncode == 0 and len(lines) >= 2:
            print("import       :", lines[0])
            print("features     :", lines[1])
        else:
            print("import       : FAILED rc=%d  %s" % (
                out.returncode, out.stderr.strip()[-300:]))
    except subprocess.TimeoutExpired:
        print("import       : TIMED OUT (>%ds)" % timeout)


def check_accelerator(timeout=60):
    """Probe jax.devices() in a subprocess: a wedged tunnel blocks forever
    in-process; here it just times out and reports unreachable."""
    print("----------Accelerator----------")
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "print(d.platform, '|', d.device_kind, '|', len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout)
        dt = time.time() - t0
        if out.returncode == 0 and out.stdout.strip():
            print("devices      : %s  (dial %.1fs)" % (
                out.stdout.strip().splitlines()[-1], dt))
        else:
            print("devices      : FAILED rc=%d  %s" % (
                out.returncode, out.stderr.strip()[-200:]))
    except subprocess.TimeoutExpired:
        print("devices      : UNREACHABLE (dial blocked > %ds — wedged "
              "accelerator tunnel; CPU runs need JAX_PLATFORMS=cpu)"
              % timeout)


def main():
    check_python()
    check_os()
    check_deps()
    check_mxnet_tpu()
    check_accelerator(int(os.environ.get("MXTPU_DIAG_TIMEOUT_S", "60")))


if __name__ == "__main__":
    main()
