#!/usr/bin/env python
"""im2rec: pack an image dataset into RecordIO.

Equivalent of the reference's tools/im2rec.py / tools/im2rec.cc: builds a
.lst index (``--list``) from a directory tree, or packs a .lst into
``prefix.rec`` + ``prefix.idx`` readable by ImageIter / ImageRecordDataset.
Record payloads use the reference's IRHeader format (recordio.pack_img), so
datasets interchange both ways. The heavy IO path (record framing) runs
through the native C++ writer when built.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=True):
    """Yield (relative_path, label) with one label per subdirectory
    (reference: im2rec.py list_image)."""
    cat = {}
    if recursive:
        for path, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                if f.lower().endswith(_EXTS):
                    d = os.path.relpath(path, root)
                    if d not in cat:
                        cat[d] = len(cat)
                    yield os.path.join(os.path.relpath(path, root), f), cat[d]
    else:
        for f in sorted(os.listdir(root)):
            if f.lower().endswith(_EXTS):
                yield f, 0


def make_list(args):
    """Write prefix.lst: lines of 'index\\tlabel\\trelpath' (reference:
    im2rec.py make_list)."""
    items = list(list_images(args.root, recursive=not args.no_recursive))
    if args.shuffle:
        random.seed(100)
        random.shuffle(items)
    with open(args.prefix + ".lst", "w") as f:
        for i, (path, label) in enumerate(items):
            f.write("%d\t%f\t%s\n" % (i, float(label), path))
    return len(items)


def read_list(lst_path):
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack_native(args):
    """Multithreaded C++ fast path (reference: tools/im2rec.cc worker
    pipeline) — packs ORIGINAL image bytes; only valid when no recode
    (resize/crop/quality) is requested. Returns the record count, or None
    when the native library is unavailable (caller falls back)."""
    import ctypes

    from mxnet_tpu.lib import native

    lib = native.get()
    if lib is None:
        return None
    fn = lib.mxtpu_im2rec_pack
    fn.restype = ctypes.c_int64
    fn.argtypes = [ctypes.c_char_p] * 4 + [ctypes.c_int]
    n = fn((args.prefix + ".lst").encode(), args.root.encode(),
           (args.prefix + ".rec").encode(), (args.prefix + ".idx").encode(),
           int(args.num_thread))
    if n == -(2 ** 63):  # INT64_MIN: file-level open/parse/write failure
        raise OSError("im2rec native pack: cannot open, parse, or write "
                      "lst/rec/idx files (malformed .lst id or full disk?)")
    if n < 0:
        raise OSError("im2rec native pack: failed reading item %d of %s.lst"
                      % (-n - 1, args.prefix))
    return int(n)


def pack(args):
    """Pack prefix.lst -> prefix.rec + prefix.idx (reference: im2rec.py
    image_encode/write worker pipeline)."""
    import numpy as np

    from mxnet_tpu import image, recordio

    recode = bool(args.resize or args.quality != 95 or args.center_crop)
    if args.num_thread > 1 and not recode:
        n = pack_native(args)
        if n is not None:
            return n
    lst = args.prefix + ".lst"
    rec = args.prefix + ".rec"
    idx = args.prefix + ".idx"
    writer = recordio.MXIndexedRecordIO(idx, rec, "w")
    count = 0
    for i, labels, relpath in read_list(lst):
        path = os.path.join(args.root, relpath)
        with open(path, "rb") as f:
            buf = f.read()
        if recode:
            img = image.imdecode(buf, to_ndarray=False)
            if args.resize:
                img = image.resize_short(img, args.resize)
            if args.center_crop:
                h, w = img.shape[:2]
                s = min(h, w)
                y0, x0 = (h - s) // 2, (w - s) // 2
                img = img[y0:y0 + s, x0:x0 + s]
            buf = image.imencode(img, quality=args.quality,
                                 fmt="." + args.encoding)
        label = labels[0] if len(labels) == 1 else np.asarray(labels)
        header = recordio.IRHeader(0, label, i, 0)
        writer.write_idx(i, recordio.pack(header, buf))
        count += 1
    writer.close()
    return count


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="generate prefix.lst instead of packing")
    p.add_argument("--no-recursive", action="store_true")
    p.add_argument("--no-shuffle", dest="shuffle", action="store_false",
                   default=True, help="keep deterministic listing order")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter side to this many pixels")
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", choices=("jpg", "png"), default="jpg")
    p.add_argument("--num-thread", type=int, default=1,
                   help=">1 uses the multithreaded C++ packer when no "
                        "recode (resize/crop/quality) is requested "
                        "(reference: tools/im2rec.cc)")
    args = p.parse_args(argv)
    if args.list:
        n = make_list(args)
        print("wrote %s.lst (%d items)" % (args.prefix, n))
    else:
        if not os.path.exists(args.prefix + ".lst"):
            n = make_list(args)
            print("wrote %s.lst (%d items)" % (args.prefix, n))
        n = pack(args)
        print("packed %d records -> %s.rec" % (n, args.prefix))


if __name__ == "__main__":
    main()
