#!/usr/bin/env python
"""Communication micro-benchmark (reference: tools/bandwidth/measure.py —
times kvstore push+pull of model-sized gradient arrays across devices).

Two layers are measured, mirroring how the reference separates kvstore
strategy from raw link speed:

1. ``kvstore`` mode — `kv.push` + `kv.pull` per parameter of a model-zoo
   network (the reference's default workload: resnet gradients), through
   the store type under test (`local` / `device`), optionally with 2-bit
   gradient compression (`--gc-type 2bit`).
2. ``collective`` mode — raw XLA collectives (`psum`, `all_gather`,
   `reduce_scatter`, `ppermute`) over the device mesh, the primitives the
   TPU kvstore lowers to (SURVEY §5.8: the NCCL/ps-lite replacement).

Reported number is allreduce algorithmic bandwidth
``2 * bytes * (n-1)/n / time`` per device (the standard NCCL-tests
accounting), so results are comparable across device counts.

Run on the 8-virtual-device CPU mesh (default when no accelerator):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bandwidth.py --mode collective --sizes-mb 1,16,64
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def parse_args():
    p = argparse.ArgumentParser(description="kvstore/collective bandwidth "
                                "benchmark (reference tools/bandwidth)")
    p.add_argument("--mode", choices=["kvstore", "collective"],
                   default="kvstore")
    p.add_argument("--network", type=str, default="resnet50_v1",
                   help="model-zoo network whose param shapes form the "
                        "kvstore workload (reference --network)")
    p.add_argument("--kv-store", type=str, default="device",
                   help="kvstore type to benchmark (reference --kv-store)")
    p.add_argument("--num-batches", type=int, default=5)
    p.add_argument("--gc-type", type=str, default="none",
                   help="gradient compression: none|2bit (reference "
                        "--gc-type)")
    p.add_argument("--ndev", type=int, default=2,
                   help="kvstore mode: per-key device-copy count pushed "
                        "per batch (the reference's --gpus list length)")
    p.add_argument("--test-results", type=int, default=1,
                   help="verify push+pull numerics against a local sum")
    p.add_argument("--sizes-mb", type=str, default="4,16,64",
                   help="collective mode: comma list of buffer sizes (MB)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON line per measurement")
    return p.parse_args()


def _algbw(nbytes, n_dev, dt):
    """allreduce algorithmic bandwidth per device, GB/s."""
    if dt <= 0:
        return float("inf")
    return 2.0 * nbytes * (n_dev - 1) / n_dev / dt / 1e9


def bench_kvstore(args):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net_fn = getattr(vision, args.network, None)
    if net_fn is None:
        raise SystemExit("unknown network %r (model zoo exports: %s)"
                         % (args.network, [n for n in dir(vision)
                                           if not n.startswith("_")][:20]))
    net = net_fn()
    net.initialize(mx.init.Xavier())
    x = mx.nd.zeros((1, 3, 224, 224))
    net(x)  # materialize deferred shapes

    kv = mx.kv.create(args.kv_store)
    if args.gc_type != "none":
        kv.set_gradient_compression({"type": args.gc_type})

    params = [(name, p.data()) for name, p in
              sorted(net.collect_params().items()) if p.grad_req != "null"]
    shapes = [tuple(v.shape) for _, v in params]
    total_bytes = sum(int(np.prod(s)) * 4 for s in shapes)
    rng = np.random.RandomState(0)
    grads = [mx.nd.array(rng.uniform(-1, 1, s).astype(np.float32))
             for s in shapes]
    for i, (name, _v) in enumerate(params):
        kv.init(i, mx.nd.zeros(shapes[i]))

    # each key is pushed as a list of `ndev` per-device copies — kvstore
    # sums the group and replaces the stored value (reference push
    # semantics); pull broadcasts it back. This is one allreduce per param.
    ndev = args.ndev
    results = []
    for batch in range(args.num_batches):
        t0 = time.perf_counter()
        for i in range(len(params)):
            kv.push(i, [grads[i]] * ndev)
        outs = [mx.nd.zeros(shapes[i]) for i in range(len(params))]
        for i in range(len(params)):
            kv.pull(i, out=outs[i])
        for o in outs:
            o.wait_to_read()
        dt = time.perf_counter() - t0
        results.append(dt)
        row = {"batch": batch, "time_s": round(dt, 4),
               "mb": round(total_bytes / 1e6, 2), "ndev": ndev,
               "gbps": round(_algbw(total_bytes, ndev, dt), 3)}
        print(json.dumps(row) if args.json else
              "batch %(batch)d: %(mb).1f MB x%(ndev)d pushed+pulled in "
              "%(time_s).3fs (%(gbps).2f GB/s)" % row)

    if args.test_results and args.gc_type == "none":
        # stored value = sum of the ndev pushed copies (reference
        # tools/bandwidth/measure.py error check: pulled vs ndev * grad)
        got = outs[0].asnumpy()
        want = grads[0].asnumpy() * ndev
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        print("numerics ok (stored = %d x grad)" % ndev)
    best = min(results)
    print("%s: %d params, %.1f MB, best %.3fs"
          % (args.kv_store, len(params), total_bytes / 1e6, best))


def bench_collective(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel import make_mesh, named_sharding

    devs = jax.devices()
    n = len(devs)
    mesh = make_mesh([("dp", n)], devices=devs)
    from jax.sharding import PartitionSpec as P

    sh = named_sharding(mesh, P("dp"))
    repl = named_sharding(mesh, P())

    ops = {
        "psum": (lambda x: jax.lax.psum(x, "dp"), sh, repl),
        "all_gather": (lambda x: jax.lax.all_gather(x, "dp", tiled=True),
                       sh, repl),
        "reduce_scatter": (
            lambda x: jax.lax.psum_scatter(x, "dp", tiled=True), sh, sh),
        "ppermute": (lambda x: jax.lax.ppermute(
            x, "dp", [(i, (i + 1) % n) for i in range(n)]), sh, sh),
    }

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    for size_mb in (float(s) for s in args.sizes_mb.split(",")):
        nfloat = int(size_mb * 1e6 / 4)
        # divisible by n^2: shard_map splits by n, reduce_scatter again by n
        nfloat = max(n * n, nfloat - nfloat % (n * n))
        x = jnp.arange(nfloat, dtype=jnp.float32)
        nbytes = nfloat * 4
        for name, (fn, in_sh, out_sh) in ops.items():
            try:
                body = shard_map(fn, mesh=mesh, in_specs=P("dp"),
                                 out_specs=out_sh.spec, check_vma=False)
            except TypeError:  # pre-0.9 jax uses check_rep
                body = shard_map(fn, mesh=mesh, in_specs=P("dp"),
                                 out_specs=out_sh.spec, check_rep=False)
            f = jax.jit(body, in_shardings=in_sh, out_shardings=out_sh)
            xd = jax.device_put(x, in_sh)
            f(xd).block_until_ready()  # compile
            t0 = time.perf_counter()
            iters = 10
            for _ in range(iters):
                out = f(xd)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / iters
            row = {"collective": name, "mb": round(nbytes / 1e6, 2),
                   "n_dev": n, "time_ms": round(dt * 1e3, 3),
                   "algbw_gbps": round(_algbw(nbytes, n, dt), 3)}
            print(json.dumps(row) if args.json else
                  "%(collective)14s %(mb)8.1f MB x%(n_dev)d: "
                  "%(time_ms)8.3f ms  %(algbw_gbps)8.2f GB/s" % row)


def main():
    # a sitecustomize PJRT hook force-overrides jax_platforms at interpreter
    # start; re-assert the env's explicit choice (same guard as bench.py)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    args = parse_args()
    if args.mode == "collective":
        bench_collective(args)
    else:
        bench_kvstore(args)


if __name__ == "__main__":
    main()
