#!/bin/bash
# Opportunistic on-chip capture: everything the perf program needs from ONE
# tunnel window. Probes the accelerator in a loop (a wedged axon PJRT dial
# blocks jax.devices() forever — each probe is a fresh subprocess under
# `timeout`); the moment the chip answers it runs, in decision-relevance
# order: train + score benches, the op-level step profile, the BN bisect,
# the remaining bench modes, and the real-chip smoke suite.
#
# Usage: tools/bench_capture.sh [tag]      (default tag: local_r04b)
set -u
cd "$(dirname "$0")/.."
TAG="${1:-local_r04b}"
PROBE_TIMEOUT="${MXTPU_PROBE_TIMEOUT:-120}"
SLEEP="${MXTPU_PROBE_INTERVAL:-60}"
# total wall-clock budget for the probe loop: a down tunnel fails FAST with
# a stale-labeled artifact instead of retrying blind for 75+ minutes (the
# round-5 failure mode). Backoff doubles per failed probe, capped.
PROBE_DEADLINE="${MXTPU_PROBE_DEADLINE:-1800}"
SLEEP_MAX="${MXTPU_PROBE_INTERVAL_MAX:-300}"

# device-topology cache (runtime.dial_devices writes it on every
# successful non-CPU dial): failed/stale rows can still name the hardware
# they missed, and the flight recorder brackets every dial attempt
export MXTPU_TOPOLOGY_CACHE="${MXTPU_TOPOLOGY_CACHE:-BENCH_${TAG}_topology.json}"

probe() {
  timeout "$PROBE_TIMEOUT" python -c "
from mxnet_tpu.runtime import dial_devices
d = dial_devices(timeout_s=max(1, $PROBE_TIMEOUT - 5))[0]
print(d.platform, d.device_kind)
" 2>/dev/null
}

# offline evidence first (CPU, no accelerator needed): HLO-diff + FLOP/byte
# notes for every perf-sensitive segment at this SHA land in
# docs/perf_evidence/ even if the tunnel never opens this round
echo "[bench_capture] generating offline perf evidence (CPU)" >&2
JAX_PLATFORMS=cpu timeout 900 python tools/perf_evidence.py >&2 || \
  echo "[bench_capture] perf_evidence FAILED (continuing)" >&2

echo "[bench_capture] probing accelerator (deadline ${PROBE_DEADLINE}s)..." >&2
PROBE_START=$(date +%s)
BACKOFF="$SLEEP"
while true; do
  KIND=$(probe) && [ -n "$KIND" ] && break
  ELAPSED=$(( $(date +%s) - PROBE_START ))
  if [ "$ELAPSED" -ge "$PROBE_DEADLINE" ]; then
    # stale-labeled artifact: downstream tooling sees an explicit
    # tunnel-down record at this SHA instead of silently-missing files
    SHA=$(git rev-parse --short HEAD 2>/dev/null || echo unknown)
    printf '{"error": "accelerator unreachable", "stale": true, "probe_deadline_s": %s, "elapsed_s": %s, "sha": "%s", "utc": "%s"}\n' \
      "$PROBE_DEADLINE" "$ELAPSED" "$SHA" "$(date -u +%FT%TZ)" \
      > "BENCH_${TAG}_stale.json"
    echo "[bench_capture] tunnel never opened within ${PROBE_DEADLINE}s;" \
         "wrote BENCH_${TAG}_stale.json and giving up" >&2
    exit 3
  fi
  echo "[bench_capture] $(date -u +%H:%M:%S) probe failed/hung (${ELAPSED}s/${PROBE_DEADLINE}s); retry in ${BACKOFF}s" >&2
  sleep "$BACKOFF"
  BACKOFF=$(( BACKOFF * 2 )); [ "$BACKOFF" -gt "$SLEEP_MAX" ] && BACKOFF="$SLEEP_MAX"
done
echo "[bench_capture] device up: $KIND" >&2

# per-row dial budget: starts at 300s; the FIRST unreachable-tunnel row
# drops it to 60s so a mid-capture tunnel collapse fails the remaining
# rows fast (stale-labeled) instead of burning 300-900s each
DIAL_RETRY=300

run_one() {  # run_one <suffix> [extra ENV=VAL ...]
  local SUFFIX="$1"; shift
  local OUT="BENCH_${TAG}_${SUFFIX}.json"
  # per-run telemetry (docs/observability.md): each bench row runs with a
  # fresh MXTPU_TELEMETRY_DIR whose JSONL gets archived next to the
  # BENCH artifact — step timings / jit-cache / collective counters at the
  # exact SHA+config of every number we publish
  local TDIR
  TDIR=$(mktemp -d "telemetry_${TAG}_${SUFFIX}.XXXX")
  echo "[bench_capture] running $SUFFIX -> $OUT" >&2
  env "$@" MXTPU_BENCH_DIAL_RETRY_S="$DIAL_RETRY" MXTPU_TELEMETRY_DIR="$TDIR" \
    timeout 1800 python bench.py > "$OUT" 2> "BENCH_${TAG}_${SUFFIX}.log"
  local RC=$?
  if [ "$RC" = "124" ]; then
    # a slow-tunnel timeout still seeded the persistent compile cache
    # (bench.py arms it post-dial), so one retry resumes past the
    # already-compiled executables instead of starting from zero
    echo "[bench_capture] $SUFFIX timed out; retrying once on warm cache" >&2
    env "$@" MXTPU_BENCH_DIAL_RETRY_S="$DIAL_RETRY" MXTPU_TELEMETRY_DIR="$TDIR" \
      timeout 1800 python bench.py > "$OUT" 2>> "BENCH_${TAG}_${SUFFIX}.log"
    RC=$?
  fi
  if grep -q '"error": "accelerator tunnel unreachable' "$OUT" 2>/dev/null; then
    # the dial died mid-capture: label this row's artifact stale (its JSON
    # already carries the stale fallback numbers) and fail the remaining
    # rows fast instead of burning $DIAL_RETRY seconds per row
    mv "$OUT" "BENCH_${TAG}_${SUFFIX}_stale.json"
    OUT="BENCH_${TAG}_${SUFFIX}_stale.json"
    if [ "$DIAL_RETRY" != "60" ]; then
      echo "[bench_capture] tunnel collapsed mid-capture; remaining rows fail fast (60s dial budget)" >&2
      DIAL_RETRY=60
    fi
  fi
  # archive whatever telemetry the run flushed (concatenated across
  # pids/ranks; empty runs leave no artifact)
  if ls "$TDIR"/*.jsonl >/dev/null 2>&1; then
    cat "$TDIR"/*.jsonl > "BENCH_${TAG}_${SUFFIX}_telemetry.jsonl"
  fi
  rm -rf "$TDIR"
  echo "[bench_capture] $SUFFIX rc=$RC $(cat "$OUT" 2>/dev/null | head -c 300)" >&2
}

# decision-relevant first: the post-BN/maxpool-fix train number
run_one train           MXTPU_BENCH_MODE=train
run_one score           MXTPU_BENCH_MODE=score

# hot-path promotion A/B (docs/sharded_training.md): op-by-op gluon loop
# vs the fused ShardedTrainer whole-step executable on a dispatch-bound
# MLP. The fused row times BOTH impls in-process (speedup, per-step
# dispatch delta, donation aliased_fraction, data-wait/compute split);
# the opbyop row pins the op-by-op number on its own trajectory
run_one train_sharded_opbyop MXTPU_BENCH_MODE=train_sharded \
                             MXTPU_BENCH_SHARDED_IMPL=opbyop \
                             MXTPU_BENCH_BATCH=256
run_one train_sharded_fused  MXTPU_BENCH_MODE=train_sharded \
                             MXTPU_BENCH_SHARDED_IMPL=fused \
                             MXTPU_BENCH_BATCH=256

# input-pipeline A/B (docs/data_pipeline.md): sync next() vs the
# DevicePrefetcher double buffer over a deliberately stalled iterator —
# data_wait_fraction both arms, loss-trajectory equality self-check
run_one input           MXTPU_BENCH_MODE=train_input \
                        MXTPU_BENCH_BATCH=256

echo "[bench_capture] step profile" >&2
rm -rf step_trace
PYTHONPATH=".:${PYTHONPATH:-}" timeout 1200 python tools/step_profile.py 256 \
  > "PROFILE_${TAG}.json" 2> "PROFILE_${TAG}.log"
echo "[bench_capture] profile rc=$?" >&2

echo "[bench_capture] bn bisect" >&2
PYTHONPATH=".:${PYTHONPATH:-}" timeout 1500 python tools/bn_bisect.py \
  > "BISECT_${TAG}.json" 2> "BISECT_${TAG}.log"
echo "[bench_capture] bisect rc=$?" >&2

run_one train_nhwc      MXTPU_BENCH_MODE=train MXTPU_BENCH_LAYOUT=NHWC
run_one score_nhwc      MXTPU_BENCH_MODE=score MXTPU_BENCH_LAYOUT=NHWC

# conv-epilogue + space-to-depth stem A-B (the round-6 fusion work): off /
# fused / stem / combined, all NHWC train — one window answers the whole
# comparison without further code changes
run_one train_nhwc_epioff      MXTPU_BENCH_MODE=train MXTPU_BENCH_LAYOUT=NHWC \
                               MXTPU_PALLAS_CONV_EPILOGUE=0
run_one train_nhwc_epifuse     MXTPU_BENCH_MODE=train MXTPU_BENCH_LAYOUT=NHWC \
                               MXTPU_PALLAS_CONV_EPILOGUE=1
run_one train_nhwc_s2d         MXTPU_BENCH_MODE=train MXTPU_BENCH_LAYOUT=NHWC \
                               MXTPU_PALLAS_CONV_EPILOGUE=0 MXTPU_S2D_STEM=1
run_one train_nhwc_epifuse_s2d MXTPU_BENCH_MODE=train MXTPU_BENCH_LAYOUT=NHWC \
                               MXTPU_PALLAS_CONV_EPILOGUE=1 MXTPU_S2D_STEM=1
run_one score_resnet152 MXTPU_BENCH_MODE=score MXTPU_BENCH_NET=resnet152
run_one score_inception MXTPU_BENCH_MODE=score MXTPU_BENCH_NET=inception_v3
run_one train_inception MXTPU_BENCH_MODE=train MXTPU_BENCH_NET=inception_v3 MXTPU_BENCH_BATCH=128
run_one train_alexnet   MXTPU_BENCH_MODE=train MXTPU_BENCH_NET=alexnet MXTPU_BENCH_BATCH=256
run_one score_int8      MXTPU_BENCH_MODE=score_int8
echo "[bench_capture] int8 probe" >&2
PYTHONPATH=".:${PYTHONPATH:-}" timeout 900 python tools/int8_probe.py \
  > "INT8_PROBE_${TAG}.jsonl" 2> "INT8_PROBE_${TAG}.log"
echo "[bench_capture] int8 probe rc=$?" >&2
run_one bert            MXTPU_BENCH_MODE=bert
run_one lstm            MXTPU_BENCH_MODE=lstm
run_one lstm_scan       MXTPU_BENCH_MODE=lstm MXTPU_PALLAS_LSTM=0

# serving: dynamic-batching inference over resnet18 (docs/serving.md) —
# closed-loop speedup vs sequential, open-loop latency, batch occupancy,
# and the zero-recompile-after-warmup proof, with the full telemetry JSONL
# (queue depth / occupancy / jit events) archived next to the artifact
echo "[bench_capture] serve bench (resnet18)" >&2
SERVE_TDIR=$(mktemp -d "telemetry_${TAG}_serve.XXXX")
env MXTPU_TELEMETRY_DIR="$SERVE_TDIR" PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 1500 python tools/serve_bench.py --net resnet18 \
  --clients 32 --requests 12 --open-rate 100 \
  > "BENCH_${TAG}_serve_resnet18.json" 2> "BENCH_${TAG}_serve_resnet18.log"
echo "[bench_capture] serve bench rc=$?" >&2
if ls "$SERVE_TDIR"/*.jsonl >/dev/null 2>&1; then
  cat "$SERVE_TDIR"/*.jsonl > "BENCH_${TAG}_serve_resnet18_telemetry.jsonl"
fi
rm -rf "$SERVE_TDIR"

# serving generation: the decode row (docs/serving.md §Generation) —
# continuous batching + paged KV cache over a tiny decoder-only LM:
# tokens/sec, inter-token p99, KV-page peak occupancy, and the
# zero-jit-compile-after-warm proof, with the scheduler's telemetry
# (kv gauges, decode counters, intertoken histogram) archived
echo "[bench_capture] serve bench (decode)" >&2
DEC_TDIR=$(mktemp -d "telemetry_${TAG}_decode.XXXX")
env MXTPU_TELEMETRY_DIR="$DEC_TDIR" PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 900 python tools/serve_bench.py --generate \
  --clients 16 --requests 8 \
  > "BENCH_${TAG}_decode.json" 2> "BENCH_${TAG}_decode.log"
echo "[bench_capture] serve decode rc=$?" >&2
if ls "$DEC_TDIR"/*.jsonl >/dev/null 2>&1; then
  cat "$DEC_TDIR"/*.jsonl > "BENCH_${TAG}_decode_telemetry.jsonl"
fi
rm -rf "$DEC_TDIR"

# serving resilience: the failover row (docs/serving.md chaos playbook) —
# SIGKILL one replica of a 2-replica pool mid-run; the evidence is
# error-rate 0 with every request resolving 200/429/503/504, loss-window
# throughput > 0, and the recovery-time-to-healthy, with the pool's
# telemetry (healthy gauge, failover/restart counters, eject events)
# archived next to the artifact
echo "[bench_capture] serve bench (failover)" >&2
FAIL_TDIR=$(mktemp -d "telemetry_${TAG}_failover.XXXX")
env MXTPU_TELEMETRY_DIR="$FAIL_TDIR" PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 900 python tools/serve_bench.py --failover --replicas 2 \
  > "BENCH_${TAG}_failover.json" 2> "BENCH_${TAG}_failover.log"
echo "[bench_capture] serve failover rc=$?" >&2
if ls "$FAIL_TDIR"/*.jsonl >/dev/null 2>&1; then
  cat "$FAIL_TDIR"/*.jsonl > "BENCH_${TAG}_failover_telemetry.jsonl"
fi
rm -rf "$FAIL_TDIR"

# serving elasticity: the autoscale row (docs/serving.md §Autoscaling
# surge playbook) — open-loop surge over a 1-replica pool with the
# autoscaler armed; the evidence is the measured scale-up latency (surge
# start -> grown pool serving), the p99-verdict recovery time, the idle
# scale-down, zero 500s, and the decision counters/events archived in
# the telemetry JSONL next to the artifact
echo "[bench_capture] serve bench (autoscale)" >&2
ASC_TDIR=$(mktemp -d "telemetry_${TAG}_autoscale.XXXX")
env MXTPU_TELEMETRY_DIR="$ASC_TDIR" PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 900 python tools/serve_bench.py --autoscale \
  > "BENCH_${TAG}_autoscale.json" 2> "BENCH_${TAG}_autoscale.log"
echo "[bench_capture] serve autoscale rc=$?" >&2
if ls "$ASC_TDIR"/*.jsonl >/dev/null 2>&1; then
  cat "$ASC_TDIR"/*.jsonl > "BENCH_${TAG}_autoscale_telemetry.jsonl"
fi
rm -rf "$ASC_TDIR"

# cold start: serving replica time-to-ready, cold vs persistent-warm
# compile cache (docs/compile_cache.md) — run 1 populates an empty
# MXTPU_COMPILE_CACHE dir, run 2's fresh replica must reach ready with
# ZERO jit_compile events (rc=4 if it compiled anything) and measurably
# lower time-to-ready; the workers' telemetry JSONL is archived beside
# the row
echo "[bench_capture] cold start (resnet18, compile cache)" >&2
COLD_TDIR=$(mktemp -d "telemetry_${TAG}_coldstart.XXXX")
env PYTHONPATH=".:${PYTHONPATH:-}" TMPDIR="$COLD_TDIR" \
  timeout 1500 python tools/coldstart_bench.py --net resnet18 \
  > "BENCH_${TAG}_coldstart.json" 2> "BENCH_${TAG}_coldstart.log"
echo "[bench_capture] cold start rc=$?" >&2
if ls "$COLD_TDIR"/coldstart_bench_*/telemetry_*/*.jsonl >/dev/null 2>&1; then
  cat "$COLD_TDIR"/coldstart_bench_*/telemetry_*/*.jsonl \
    > "BENCH_${TAG}_coldstart_telemetry.jsonl"
fi
rm -rf "$COLD_TDIR"

# fused-restart cold start: TRAINING time-to-step-1, cold vs warm
# persistent cache (docs/sharded_training.md) — the quarantine-lift
# proof: a restarted promoted-trainer life must reach step 1 with ZERO
# jit_compile events (rc=4 otherwise), riding the warmup manifest its
# cold life wrote
echo "[bench_capture] train restart (fused sharded step, compile cache)" >&2
TRB_TDIR=$(mktemp -d "telemetry_${TAG}_train_restart.XXXX")
env PYTHONPATH=".:${PYTHONPATH:-}" TMPDIR="$TRB_TDIR" \
  timeout 900 python tools/train_restart_bench.py \
  > "BENCH_${TAG}_train_restart.json" 2> "BENCH_${TAG}_train_restart.log"
echo "[bench_capture] train restart rc=$?" >&2
if ls "$TRB_TDIR"/train_restart_bench_*/telemetry_*/*.jsonl >/dev/null 2>&1; then
  cat "$TRB_TDIR"/train_restart_bench_*/telemetry_*/*.jsonl \
    > "BENCH_${TAG}_train_restart_telemetry.jsonl"
fi
rm -rf "$TRB_TDIR"

# preemption row: sync-vs-async checkpoint stall A/B + measured
# steps-lost contrast (docs/fault_tolerance.md §Preemption) — the async
# writer's per-save trainer stall must stay an order of magnitude under
# the synchronous serialize+fsync, and a graceful preemption must lose
# zero steps where a hard kill loses up to a save period
echo "[bench_capture] train preempt (checkpoint stall A/B)" >&2
env PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 900 python tools/train_restart_bench.py --mode preempt \
  > "BENCH_${TAG}_preempt.json" 2> "BENCH_${TAG}_preempt.log"
echo "[bench_capture] train preempt rc=$?" >&2

# memory row: the serving memory budget's evidence (docs/observability.md
# §Memory) — per-bucket memory_analysis footprint, over-budget load
# rejected / within-budget accepted / warn-mode canary, and the donation
# verifier confirming the fused trainer step aliases its donated buffers
echo "[bench_capture] serve memory budget" >&2
env PYTHONPATH=".:${PYTHONPATH:-}" \
  timeout 900 python tools/memory_bench.py \
  > "BENCH_${TAG}_memory.json" 2> "BENCH_${TAG}_memory.log"
echo "[bench_capture] serve memory rc=$?" >&2

# trace row: render the archived telemetry JSONL (serve_bench samples
# every request at --trace-sample 1.0, so the serve rows' JSONL carries
# the full span stream) into perfetto-loadable merged traces next to the
# raw JSONL — `--trace <id>` on the slowest_request id from the serve
# JSON zooms to the worst request (docs/observability.md §Tracing)
echo "[bench_capture] trace merge" >&2
for ROW in serve_resnet18 failover; do
  JSONL="BENCH_${TAG}_${ROW}_telemetry.jsonl"
  if [ -s "$JSONL" ]; then
    PYTHONPATH=".:${PYTHONPATH:-}" timeout 300 python tools/trace_merge.py \
      "$JSONL" -o "BENCH_${TAG}_${ROW}_trace.json" \
      2>> "BENCH_${TAG}_${ROW}.log" \
      && echo "[bench_capture] trace row: BENCH_${TAG}_${ROW}_trace.json" >&2
  fi
done

echo "[bench_capture] running tpu smoke suite" >&2
MXTPU_TEST_TPU=1 timeout 1800 python -m pytest tests/test_tpu_smoke.py -v \
  > "TPU_SMOKE_${TAG}.log" 2>&1
echo "[bench_capture] smoke rc=$?" >&2

# refresh the committed bench trajectory (docs/bench_trajectory.md +
# BENCH_TRAJECTORY.json) so this capture's rows land in the reviewer table
echo "[bench_capture] bench history" >&2
PYTHONPATH=".:${PYTHONPATH:-}" timeout 120 python tools/bench_history.py \
  2>> /dev/stderr || echo "[bench_capture] bench history failed" >&2

# regression gate over the refreshed trajectory, WARN-ONLY here (a capture
# must land even when it regressed — the table in the log is the signal;
# CI/reviewers run `python -m tools.bench_history --check` blocking)
PYTHONPATH=".:${PYTHONPATH:-}" timeout 120 python tools/bench_history.py \
  --check 2>> /dev/stderr \
  || echo "[bench_capture] WARNING: bench_history --check flagged a >15% headline regression (see table above)" >&2
echo "[bench_capture] done" >&2
