"""Cold-start bench: serving replica time-to-ready, cold vs warm
persistent compile cache (docs/compile_cache.md).

Exports a model, then spawns a 1-replica pool TWICE against the same
`MXTPU_COMPILE_CACHE` directory:

  * run 1 (**cold**): empty cache — every bucket executable is traced
    and compiled; the warm writes the artifacts + the warmup manifest;
  * run 2 (**warm**): a fresh worker process prefetches the manifest and
    deserializes every executable — the acceptance contract is ZERO
    ``jit_compile`` events in its telemetry and a measurably lower
    time-to-ready.

Each run's worker telemetry JSONL is read back for the jit_compile /
compile_persist_hit counts; the JSON row lands on stdout
(`bench_capture.sh` archives it as ``BENCH_<tag>_coldstart.json``).

Usage: python tools/coldstart_bench.py [--net resnet18|mlp]
       [--image-size 32] [--max-batch 8] [--cache-dir DIR]
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def log(msg):
    sys.stderr.write("[coldstart_bench] %s\n" % msg)
    sys.stderr.flush()


def _jsonl_events(tdir):
    counts = {}
    for name in sorted(os.listdir(tdir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(tdir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "event":
                    ev = rec.get("event")
                    counts[ev] = counts.get(ev, 0) + 1
    return counts


def _spawn_run(tag, prefix, input_shapes, max_batch, cache_dir, workdir,
               timeout_s):
    from mxnet_tpu.serving.model_repository import ServedModel
    from mxnet_tpu.telemetry import memory as _tm_memory

    import numpy as np

    tdir = os.path.join(workdir, "telemetry_" + tag)
    os.makedirs(tdir, exist_ok=True)
    t0 = time.monotonic()
    model = ServedModel.pooled(
        "coldstart", 1, prefix, replicas=1, input_shapes=input_shapes,
        max_batch=max_batch,
        extra_env={"MXTPU_COMPILE_CACHE": cache_dir,
                   "MXTPU_TELEMETRY_DIR": tdir},
        spawn_timeout_s=timeout_s)
    ready_s = time.monotonic() - t0
    try:
        shape = (2,) + tuple(input_shapes["data"])
        out = model.predict({"data": np.zeros(shape, np.float32)},
                            timeout_ms=60000)
        buckets = list(model.buckets)
        row = {
            "ready_s": round(ready_s, 3),
            "worker_warm_s": round(model.warm_seconds or 0.0, 3),
            "buckets": buckets,
            "first_predict_ok": bool(out and out[0].shape[0] == 2),
            "compile_digests": len(model.compile_digests),
            # ready-frame memory attribution + this phase's peak RSS
            # (docs/observability.md §Memory)
            "model_memory_bytes": model.memory_bytes,
            "memory": _tm_memory.read_process_memory(),
        }
    finally:
        model.close(drain=True, timeout=10)
    time.sleep(1.0)  # let the worker's exit flush land
    events = _jsonl_events(tdir)
    row["jit_compiles"] = events.get("jit_compile", 0)
    row["persist_hits"] = events.get("compile_persist_hit", 0)
    row["persist_bad"] = events.get("compile_persist_bad", 0)
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--net", choices=("mlp", "resnet18"), default="resnet18")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--cache-dir", default=None,
                   help="persistent cache dir (default: fresh temp dir)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-run spawn->ready budget (seconds)")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the bench process itself must not populate the cache the COLD run
    # is supposed to find empty
    os.environ.pop("MXTPU_COMPILE_CACHE", None)

    from serve_bench import _build_mlp, _build_resnet18  # noqa: E402

    workdir = tempfile.mkdtemp(prefix="coldstart_bench_")
    cache_dir = args.cache_dir or os.path.join(workdir, "compile_cache")
    os.makedirs(cache_dir, exist_ok=True)

    log("building %s ..." % args.net)
    if args.net == "mlp":
        prefix, input_shapes = _build_mlp(workdir)
    else:
        prefix, input_shapes = _build_resnet18(workdir, args.image_size)

    log("run 1/2: COLD (empty cache %s)" % cache_dir)
    cold = _spawn_run("cold", prefix, input_shapes, args.max_batch,
                      cache_dir, workdir, args.timeout)
    log("cold: ready %.2fs, warm %.2fs, %d jit_compiles"
        % (cold["ready_s"], cold["worker_warm_s"], cold["jit_compiles"]))

    artifacts = 0
    artifact_bytes = 0
    objects = os.path.join(cache_dir, "objects")
    if os.path.isdir(objects):
        for name in os.listdir(objects):
            artifacts += 1
            artifact_bytes += os.path.getsize(os.path.join(objects, name))

    log("run 2/2: WARM (populated cache)")
    warm = _spawn_run("warm", prefix, input_shapes, args.max_batch,
                      cache_dir, workdir, args.timeout)
    log("warm: ready %.2fs, warm %.2fs, %d jit_compiles, %d persist hits"
        % (warm["ready_s"], warm["worker_warm_s"], warm["jit_compiles"],
           warm["persist_hits"]))

    try:
        sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))),
                             timeout=10).stdout.strip() or None
    except Exception:
        sha = None
    result = {
        "metric": "coldstart_%s_mb%d" % (args.net, args.max_batch),
        "net": args.net,
        "max_batch": args.max_batch,
        "image_size": args.image_size if args.net == "resnet18" else None,
        "cold": cold,
        "warm": warm,
        "ready_speedup": round(cold["ready_s"] / warm["ready_s"], 2)
        if warm["ready_s"] else None,
        "warm_speedup": round(
            cold["worker_warm_s"] / warm["worker_warm_s"], 2)
        if warm["worker_warm_s"] else None,
        "zero_compile_on_warm": warm["jit_compiles"] == 0,
        "cache_artifacts": artifacts,
        "cache_bytes": artifact_bytes,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "device",
        "sha": sha,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    # acceptance: the warm replica must not have compiled anything
    return 0 if result["zero_compile_on_warm"] else 4


if __name__ == "__main__":
    sys.exit(main())
