#!/usr/bin/env python
"""serve_bench: open/closed-loop load generator for the serving subsystem
(docs/serving.md load-test playbook).

Builds (or loads) a model, serves it in-process through the real HTTP
stack (`ServingServer` on 127.0.0.1), and measures four phases:

  1. ``sequential`` — one closed-loop client, single-example requests:
     the predict-API baseline the batcher must beat.
  2. ``batched`` — N closed-loop clients, single-example requests: the
     dynamic-batching payoff at the SAME per-request deadline budget.
  3. ``mixed`` — N clients with varying per-request example counts:
     exercises every padding bucket; the executable-cache proof is that
     ZERO ``jit_compile`` events fire in this phase (warmup covered all
     buckets).
  4. ``open`` (optional, ``--open-rate``) — Poisson arrivals at a fixed
     rate: latency under a load the server does not control.

``--generate`` runs the decode row instead (docs/serving.md
§Generation): a tiny decoder-only LM is exported and served through the
continuous-batching scheduler + paged KV cache, N closed-loop clients
fire ``:generate`` requests with RANDOM prompt lengths and UNEQUAL
``max_new_tokens`` (the workload shape batch-synchronous serving cannot
batch), and the row reports tokens/sec, inter-token p50/p99 from the
``mxtpu_serve_intertoken_seconds`` histogram, KV-page peak occupancy,
and the post-warm jit-compile count (must be 0).

``--autoscale`` runs the elasticity row instead (docs/serving.md
§Autoscaling surge playbook): the model is served through a 1-replica
pool with the `Autoscaler` armed, an open-loop surge overdrives it, and
the row reports the measured scale-up latency (surge start -> the grown
pool fully serving), the p99-verdict recovery time, the idle
scale-down, the decision counters, and that no request answered 500.
Closed-loop clients in every row honor ``Retry-After`` on 429/503
(the honored count rides the JSON) — hammering a shedding server both
skews the loss-window rps and fights the recovery window.

``--failover`` runs the resilience row instead (docs/serving.md
chaos-testing playbook): the model is served through a supervised
``--replicas N`` pool, a closed-loop workload runs for
``--failover-duration`` seconds, and ``--kill-after`` seconds in one
replica is SIGKILLed mid-run. The row reports the error-rate and
status-code breakdown (every request must resolve to 200/429/503/504 —
nothing silently dropped), throughput overall and DURING the
single-replica loss window (must stay > 0), and the
recovery-time-to-healthy measured from the kill to the respawned
replica's ready heartbeat.

Emits one JSON document on stdout: p50/p99 latency, throughput,
speedup over sequential, batch occupancy, error counts by status, and
the jit-compile-after-warmup count. Run under a fresh
``MXTPU_TELEMETRY_DIR`` to archive the full metrics JSONL next to the
result (tools/bench_capture.sh `serve_resnet18` / `serve_failover`
rows do).

Offline evidence (CPU):

  JAX_PLATFORMS=cpu python tools/serve_bench.py > BENCH_serve.json
  JAX_PLATFORMS=cpu python tools/serve_bench.py --failover \
      > BENCH_failover.json
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# model builders
# ---------------------------------------------------------------------------

def _build_mlp(tmpdir):
    """A BLAS-bound MLP: per-call overhead dominates single-request serving,
    so batching headroom is visible even on CPU."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential(prefix="bench_")
    with net.name_scope():
        net.add(gluon.nn.Dense(1024, activation="relu"),
                gluon.nn.Dense(1024, activation="relu"),
                gluon.nn.Dense(1024, activation="relu"),
                gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 64), np.float32)))
    prefix = os.path.join(tmpdir, "mlp")
    net.export(prefix, epoch=0)
    return prefix, {"data": (64,)}


def _build_resnet18(tmpdir, image_size):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    shape = (3, image_size, image_size)
    net(mx.nd.array(np.zeros((1,) + shape, np.float32)))
    prefix = os.path.join(tmpdir, "resnet18")
    net.export(prefix, epoch=0)
    return prefix, {"data": shape}


def _build_lm(tmpdir, vocab=512):
    """A small decoder-only LM (2 layers, d=64) exported as a generation
    artifact — big enough that a decode step does real matmuls, small
    enough that the CPU row stays fast. NOTE: this geometry (4 heads,
    head_dim 16) is NOT (8, 128)-tile-aligned; on real TPU the paged
    kernel would take its padded-copy branch, so a silicon capture
    should serve an aligned model instead (the result carries a
    `tile_aligned` flag so the row is honest either way)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerLM
    from mxnet_tpu.serving import save_lm

    lm = TransformerLM(vocab_size=vocab, units=64, hidden_size=128,
                       num_layers=2, num_heads=4, max_length=128)
    lm.initialize(mx.init.Xavier(), ctx=mx.cpu())
    return save_lm(lm, os.path.join(tmpdir, "lm")), vocab


def _hist_quantile(snap_entry, q):
    """Approximate a quantile from a cumulative-bucket histogram
    snapshot (upper-bound of the bucket where the quantile falls)."""
    if not snap_entry or not snap_entry.get("count"):
        return None
    total = snap_entry["count"]
    items = []
    for bound, cum in snap_entry.get("buckets", {}).items():
        items.append((float("inf") if bound == "+Inf" else float(bound),
                      cum))
    items.sort()
    target = q * total
    for bound, cum in items:
        if cum >= target:
            return None if bound == float("inf") else bound
    return None


# ---------------------------------------------------------------------------
# the decode row (docs/serving.md §Generation)
# ---------------------------------------------------------------------------

def _run_generate(args, log):
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ModelRepository, ServingServer

    tmpdir = tempfile.mkdtemp(prefix="serve_bench_lm_")
    log("building + exporting LM (vocab %d) ..." % args.gen_vocab)
    prefix, vocab = _build_lm(tmpdir, vocab=args.gen_vocab)
    repo = ModelRepository()
    t0 = time.perf_counter()
    model = repo.load(
        "bench", prefix, generate=True,
        generate_opts=dict(num_pages=args.kv_pages,
                           page_size=args.kv_page_size,
                           max_prompt=args.max_prompt,
                           max_new_tokens=args.max_new_tokens,
                           max_batch=args.gen_max_batch),
        queue_depth=max(256, args.clients * 4))
    load_s = time.perf_counter() - t0
    gi = model.generate_info
    log("loaded: decode buckets %s, prefill buckets %s, kv %d pages x %d "
        "tokens, warm %.1fs"
        % (gi["decode_buckets"], gi["prefill_buckets"], gi["num_pages"],
           gi["page_size"], model.warm_seconds or 0.0))

    misses = telemetry.get_registry().counter("mxtpu_jit_cache_miss_total")
    base_miss = misses.value

    server = ServingServer(repo, port=0, addr="127.0.0.1").start()
    endpoint = ("127.0.0.1", server.port, "/v1/models/bench:generate")
    timeout_s = args.timeout_ms / 1e3 + 10.0

    # random prompts + UNEQUAL budgets: the continuous-batching workload
    rng = random.Random(0)
    nprng = np.random.RandomState(0)
    payloads = []
    for _ in range(64):
        plen = rng.randint(2, args.max_prompt)
        payloads.append(json.dumps({
            "tokens": [int(t) for t in nprng.randint(1, vocab, plen)],
            "max_new_tokens": rng.randint(max(2, args.max_new_tokens // 4),
                                          args.max_new_tokens),
            "timeout_ms": args.timeout_ms,
        }).encode())

    # KV occupancy watcher (scheduler-side gauge, sampled)
    alloc = model.scheduler.allocator
    peak = {"used": 0}
    stop = threading.Event()

    def watch():
        while not stop.is_set():
            peak["used"] = max(peak["used"], alloc.used_pages)
            time.sleep(0.002)

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()

    log("closed loop: %d clients x %d generations ..."
        % (args.clients, args.requests))
    t0 = time.perf_counter()
    phase = _closed_loop(endpoint, payloads, clients=args.clients,
                         requests_each=args.requests, timeout_s=timeout_s)
    wall = time.perf_counter() - t0
    stop.set()
    watcher.join(timeout=1.0)

    snap = telemetry.snapshot()
    label = '{model="%s/%d"}' % (model.name, model.version)
    tokens = snap.get("mxtpu_serve_generated_tokens_total" + label,
                      {}).get("value", 0)
    steps = snap.get("mxtpu_serve_decode_steps_total" + label,
                     {}).get("value", 0)
    inter = snap.get("mxtpu_serve_intertoken_seconds" + label, {})
    prefill = snap.get("mxtpu_serve_prefill_seconds" + label, {})
    # first tokens are sampled by PREFILL, not decode steps — exclude
    # them so the mean decode batch is honest occupancy, not inflated
    # by one request's worth per admission
    decode_tokens = tokens - (prefill.get("count") or 0)
    jit_after_warm = misses.value - base_miss
    p50 = _hist_quantile(inter, 0.50)
    p99 = _hist_quantile(inter, 0.99)
    result = {
        "mode": "serve_decode",
        "net": "transformer_lm",
        "device": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
                  else "default",
        # 4 heads x head_dim 16 is off the (8, 128) TPU tile grid: a
        # silicon capture of THIS geometry would measure the kernel's
        # padded-copy branch, not the zero-copy paged path
        "tile_aligned": False,
        "generate": gi,
        "clients": args.clients,
        "requests": phase["requests"],
        "codes": phase["codes"],
        "wall_s": round(wall, 3),
        "load_s": round(load_s, 2),
        "warm_s": round(model.warm_seconds or 0.0, 2),
        "generated_tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2) if wall else None,
        "decode_steps": steps,
        "mean_decode_batch": round(decode_tokens / steps, 2)
                             if steps else None,
        "request_p50_ms": phase["p50_ms"],
        "request_p99_ms": phase["p99_ms"],
        "intertoken_p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "intertoken_p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "prefill_mean_ms": round(prefill["sum"] / prefill["count"] * 1e3, 3)
                           if prefill.get("count") else None,
        "kv": {
            "pages_total": alloc.num_pages,
            "page_size": alloc.page_size,
            "peak_pages_used": peak["used"],
            "peak_occupancy": round(peak["used"] / alloc.num_pages, 3),
            "pages_used_at_drain": alloc.used_pages,
        },
        "jit_compiles_after_warmup": jit_after_warm,
        # decode rows carry health verdicts too (inter-token p99 + KV
        # occupancy objectives register at scheduler load)
        "slo": _slo_block([_slo_sample("decode")], args.slo_spec),
    }
    log("decode: %.1f tok/s, inter-token p99 %sms, kv peak %d/%d pages, "
        "jit after warm %d, pages at drain %d"
        % (result["tokens_per_sec"] or 0.0, result["intertoken_p99_ms"],
           peak["used"], alloc.num_pages, jit_after_warm,
           alloc.used_pages))
    server.drain(shutdown=True)
    telemetry.flush(reason="serve_bench_decode")
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


# ---------------------------------------------------------------------------
# load phases
# ---------------------------------------------------------------------------

def _slo_sample(phase):
    """Condensed SLO verdicts (one row per objective) sampled at a phase
    boundary — the health trail a committed bench row carries."""
    from mxnet_tpu.telemetry import slo as _slo

    return {"phase": phase, "verdicts": [
        {"slo": v["slo"], "healthy": v["healthy"], "page": v["page"],
         "ticket": v["ticket"], "no_data": v["no_data"],
         "burn_rate": v["burn_rate"], "value": v["value"],
         "budget_remaining": v["budget_remaining"]}
        for v in _slo.verdicts()]}


def _slo_block(samples, spec_path):
    """The output `slo` block: per-phase samples + the final full
    verdicts (the machine-readable health stamp next to the latency
    points)."""
    from mxnet_tpu.telemetry import slo as _slo

    return {"spec": spec_path,
            "evaluator_running": _slo.running(),
            "samples": samples,
            "final": _slo.verdicts()}


def _phase_breakdown(spans):
    """Aggregate collected span records into the per-phase latency table
    (queue / assembly / wire / compute / unpad — plus the request total)
    and find the slowest request's trace id, the one to feed
    `tools/trace_merge.py --trace <id>`."""
    by_phase = {}
    slowest = None
    for s in spans:
        name = s.get("name", "")
        if not name.startswith("serve."):
            continue
        dur_ms = (s.get("dur_us") or 0) / 1e3
        phase = name.split(".", 1)[1]
        by_phase.setdefault(phase, []).append(dur_ms)
        attrs = s.get("attrs") or {}
        if phase == "dispatch" and "wire_s" in attrs:
            # router-side split of the dispatch window: serialization +
            # hop cost vs the replica's own compute
            by_phase.setdefault("wire", []).append(attrs["wire_s"] * 1e3)
        if phase == "request" and (slowest is None
                                   or dur_ms > slowest["total_ms"]):
            slowest = {"trace_id": s.get("trace"),
                       "total_ms": round(dur_ms, 3)}
    phases = {}
    for phase, vals in sorted(by_phase.items()):
        vals.sort()
        phases[phase] = {
            "count": len(vals),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_percentile(vals, 50), 3),
            "p99_ms": round(_percentile(vals, 99), 3),
        }
    return phases, slowest


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return None
    i = min(len(sorted_ms) - 1, int(round(q * (len(sorted_ms) - 1))))
    return sorted_ms[i]


class _Client:
    """One persistent keep-alive connection (the realistic steady-client
    shape: no TCP setup or server thread spawn per request)."""

    # well-behaved clients honor Retry-After, but a bench must stay
    # bounded: a server-suggested backoff is capped here
    RETRY_AFTER_CAP_S = 5.0

    def __init__(self, host, port, path, timeout_s):
        self.host, self.port, self.path = host, port, path
        self.timeout_s = timeout_s
        self.conn = None
        self.retry_after_honored = 0

    def post(self, body):
        t0 = time.perf_counter()
        retry_after = None
        try:
            if self.conn is None:
                self.conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
            self.conn.request("POST", self.path, body=body,
                              headers={"Content-Type": "application/json"})
            r = self.conn.getresponse()
            r.read()
            code = r.status
            retry_after = r.getheader("Retry-After")
            if r.will_close:
                self.conn.close()
                self.conn = None
        except Exception:
            code = -1
            if self.conn is not None:
                self.conn.close()
                self.conn = None
        return (time.perf_counter() - t0) * 1e3, code, retry_after

    def backoff(self, code, retry_after):
        """Honor a 429/503's Retry-After before the next closed-loop
        request. Hammering a shedding server immediately both skews the
        measured loss-window rps and FIGHTS the recovery the autoscaler
        (or a respawning replica) is buying — the exact anti-pattern the
        header exists to prevent. Returns True when a backoff was
        served."""
        if code not in (429, 503) or not retry_after:
            return False
        try:
            delay = float(retry_after)
        except ValueError:
            return False
        if delay <= 0:
            return False
        time.sleep(min(delay, self.RETRY_AFTER_CAP_S))
        self.retry_after_honored += 1
        return True

    def close(self):
        if self.conn is not None:
            self.conn.close()
            self.conn = None


def _closed_loop(endpoint, payloads, clients, requests_each, timeout_s):
    """`clients` threads, each firing `requests_each` back-to-back posts
    over its own persistent connection — honoring ``Retry-After`` on
    429/503 sheds like a well-behaved client (the honored count rides
    the phase result)."""
    lats, codes, lock = [], {}, threading.Lock()
    honored = [0]

    def worker(wid):
        cli = _Client(*endpoint, timeout_s=timeout_s)
        mine = []
        my_codes = {}
        for i in range(requests_each):
            ms, code, retry_after = cli.post(
                payloads[(wid + i) % len(payloads)])
            mine.append(ms)
            my_codes[code] = my_codes.get(code, 0) + 1
            cli.backoff(code, retry_after)
        cli.close()
        with lock:
            lats.extend(mine)
            honored[0] += cli.retry_after_honored
            for c, n in my_codes.items():
                codes[c] = codes.get(c, 0) + n

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats.sort()
    total = clients * requests_each
    return {
        "requests": total,
        "wall_s": round(wall, 3),
        "rps": round(total / wall, 2),
        "p50_ms": round(_percentile(lats, 0.50), 3),
        "p99_ms": round(_percentile(lats, 0.99), 3),
        "mean_ms": round(sum(lats) / len(lats), 3),
        "codes": {str(k): v for k, v in sorted(codes.items())},
        "retry_after_honored": honored[0],
    }


def _open_loop(endpoint, payloads, rate, duration, timeout_s):
    """Poisson arrivals at `rate`/s for `duration`s (bounded concurrency)."""
    lats, codes, lock = [], {}, threading.Lock()
    sem = threading.Semaphore(256)
    threads = []
    rng = random.Random(0)

    def one(body):
        try:
            cli = _Client(*endpoint, timeout_s=timeout_s)
            ms, code, _ = cli.post(body)  # open loop: arrivals are not
            cli.close()                   # paced by the server's hints
            with lock:
                lats.append(ms)
                codes[code] = codes.get(code, 0) + 1
        finally:
            sem.release()

    t0 = time.perf_counter()
    next_t = t0
    i = 0
    while True:
        next_t += rng.expovariate(rate)
        if next_t - t0 > duration:
            break
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        sem.acquire()
        t = threading.Thread(target=one, args=(payloads[i % len(payloads)],),
                             daemon=True)
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=timeout_s + 5)
    wall = time.perf_counter() - t0
    lats.sort()
    return {
        "target_rate": rate,
        "duration_s": duration,
        "requests": len(lats),
        "achieved_rps": round(len(lats) / wall, 2) if lats else 0.0,
        "p50_ms": round(_percentile(lats, 0.50), 3) if lats else None,
        "p99_ms": round(_percentile(lats, 0.99), 3) if lats else None,
        "codes": {str(k): v for k, v in sorted(codes.items())},
    }


def _closed_loop_timed(endpoint, payloads, clients, duration_s, timeout_s):
    """`clients` threads firing back-to-back posts until `duration_s`
    elapses, honoring ``Retry-After`` on sheds (a closed-loop client
    that hammers a degraded pool skews the loss-window rps AND fights
    the recovery window). Returns per-request (t_done, ms, code) records
    (t_done on the shared perf_counter clock) plus the honored-backoff
    count, so callers can window the timeline around an injected
    failure."""
    recs, lock = [], threading.Lock()
    honored = [0]
    t0 = time.perf_counter()

    def worker(wid):
        cli = _Client(*endpoint, timeout_s=timeout_s)
        mine = []
        i = 0
        while time.perf_counter() - t0 < duration_s:
            ms, code, retry_after = cli.post(
                payloads[(wid + i) % len(payloads)])
            mine.append((time.perf_counter() - t0, ms, code))
            i += 1
            cli.backoff(code, retry_after)
        cli.close()
        with lock:
            recs.extend(mine)
            honored[0] += cli.retry_after_honored

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return t0, recs, honored[0]


def _watch_pool(pool, timeline, stop, interval_s=0.005):
    """Sample the pool's healthy-replica count into `timeline` as
    (t_perf_counter, healthy) transition records."""
    last = None
    while not stop.is_set():
        h = pool.healthy_count
        if h != last:
            timeline.append((time.perf_counter(), h))
            last = h
        time.sleep(interval_s)
    # one closing sample: the caller stops the watch the instant the pool
    # reports full health, which can land between two samples
    h = pool.healthy_count
    if h != last:
        timeline.append((time.perf_counter(), h))


def _payload(arr, timeout_ms):
    return json.dumps({"inputs": {"data": arr.tolist()},
                       "timeout_ms": timeout_ms}).encode()


# ---------------------------------------------------------------------------
# the failover row (docs/serving.md chaos-testing playbook)
# ---------------------------------------------------------------------------

def _run_failover(args, prefix, input_shapes, log):
    """Closed-loop load over a supervised replica pool with one replica
    SIGKILLed mid-run. The evidence this row commits: throughput during
    the single-replica loss stays > 0, every request resolves to a
    deterministic status (200/429/503/504 — nothing silently dropped, no
    500s), and the pool recovers to full health."""
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ModelRepository, ServingServer

    repo = ModelRepository()
    t0 = time.perf_counter()
    model = repo.load("bench", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch, max_delay_ms=args.delay_ms,
                      queue_depth=max(1024, args.clients * 4),
                      replicas=args.replicas)
    load_s = time.perf_counter() - t0
    pool = model.pool
    log("pooled load: %d replicas, buckets=%s, %.1fs (per-replica load + "
        "warm)" % (args.replicas, model.buckets, load_s))

    server = ServingServer(repo, port=0, addr="127.0.0.1").start()
    endpoint = ("127.0.0.1", server.port, "/v1/models/bench:predict")
    timeout_s = args.timeout_ms / 1e3 + 10.0
    shape = next(iter(input_shapes.values()))
    rng = np.random.RandomState(0)
    payloads = [_payload(rng.uniform(-1, 1, (1,) + shape).astype(np.float32),
                         args.timeout_ms) for _ in range(8)]

    timeline, stop = [], threading.Event()
    watcher = threading.Thread(target=_watch_pool,
                               args=(pool, timeline, stop), daemon=True)
    watcher.start()
    kill_rec = {}

    def killer():
        time.sleep(args.kill_after)
        pid = pool.replica_pid(0)
        kill_rec["t"] = time.perf_counter()
        kill_rec["pid"] = pid
        log("SIGKILL replica 0 (pid %s) at t=%.1fs" % (pid, args.kill_after))
        try:
            os.kill(pid, 9)
        except OSError as e:
            kill_rec["error"] = str(e)

    threading.Thread(target=killer, daemon=True).start()
    log("closed loop: %d clients for %.0fs, kill at %.0fs ..."
        % (args.clients, args.failover_duration, args.kill_after))
    t_run, recs, honored = _closed_loop_timed(
        endpoint, payloads, args.clients, args.failover_duration,
        timeout_s)
    # let the respawn land even when the kill came late in the window
    recovery_deadline = time.perf_counter() + 60.0
    while pool.healthy_count < args.replicas and \
            time.perf_counter() < recovery_deadline:
        time.sleep(0.02)
    stop.set()
    watcher.join(timeout=2.0)

    t_kill = kill_rec.get("t")
    recovery_s = None
    if t_kill is not None:
        recovered = [t for (t, h) in timeline
                     if t > t_kill and h >= args.replicas]
        if recovered:
            recovery_s = recovered[0] - t_kill
    loss_end = t_kill + recovery_s if (t_kill is not None
                                       and recovery_s is not None) \
        else t_run + args.failover_duration
    loss = [r for r in recs if t_kill is not None
            and t_kill <= t_run + r[0] <= loss_end]
    codes = {}
    for _, _, code in recs:
        codes[code] = codes.get(code, 0) + 1
    lats = sorted(ms for _, ms, _ in recs)
    ok = codes.get(200, 0)
    resolved = all(c in (200, 429, 503, 504) for c in codes)
    snap = telemetry.snapshot()
    label = '{model="%s/%d"}' % (model.name, model.version)

    def counter(name):
        return snap.get(name + label, {}).get("value", 0)

    wall = max(r[0] for r in recs) if recs else args.failover_duration
    result = {
        "mode": "serve_failover",
        "net": os.path.basename(args.model) if args.model else args.net,
        "device": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
                  else "default",
        "replicas": args.replicas,
        "buckets": model.buckets,
        "duration_s": args.failover_duration,
        "kill_after_s": args.kill_after,
        "load_s": round(load_s, 2),
        "requests": len(recs),
        "codes": {str(k): v for k, v in sorted(codes.items())},
        "error_rate": round(1.0 - ok / len(recs), 4) if recs else None,
        "unresolved": codes.get(-1, 0),
        "all_resolved_deterministically": resolved,
        "rps_overall": round(len(recs) / wall, 2) if recs else 0.0,
        "retry_after_honored": honored,
        "p50_ms": round(_percentile(lats, 0.50), 3) if lats else None,
        "p99_ms": round(_percentile(lats, 0.99), 3) if lats else None,
        "recovery_s": round(recovery_s, 3) if recovery_s is not None
                      else None,
        "loss_window": {
            "requests": len(loss),
            "rps": round(len(loss) / recovery_s, 2)
                   if recovery_s else None,
            "codes": {str(c): sum(1 for r in loss if r[2] == c)
                      for c in sorted({r[2] for r in loss})},
        },
        "healthy_timeline": [
            [round(t - (t_kill or t_run), 3), h] for t, h in timeline],
        "pool": {
            "failovers": counter("mxtpu_serve_failover_total"),
            "requeued": counter("mxtpu_serve_failover_requeued_total"),
            "restarts": counter("mxtpu_serve_replica_restart_total"),
            "final_healthy": pool.healthy_count,
        },
    }
    log("failover: %d reqs, codes=%s, recovery=%.2fs, loss-window rps=%s"
        % (len(recs), result["codes"], recovery_s or -1.0,
           result["loss_window"]["rps"]))
    server.drain(shutdown=True)
    telemetry.flush(reason="serve_bench_failover")
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


# ---------------------------------------------------------------------------
# the autoscale row (docs/serving.md §Autoscaling surge playbook)
# ---------------------------------------------------------------------------

def _run_autoscale(args, prefix, input_shapes, log):
    """Open-loop surge over a 1-replica pool with the autoscaler armed.
    The evidence this row commits: the surge breaches the serving SLOs,
    the pool scales up IN PLACE (measured scale-up latency = surge start
    to the new replica serving), the p99 verdict recovers (measured
    recovery time), and sustained idle drains the pool back down — with
    every request resolving deterministically (no 500s)."""
    import numpy as np

    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import (Autoscaler, ModelRepository,
                                   ServingServer)
    from mxnet_tpu.telemetry import slo as _slo

    # bench-scale SLO windows: breach + recovery must fit in a ~60s row
    # (the tier-1 chaos e2e uses the same shape at a smaller scale)
    for k, v in (("MXTPU_SLO_WINDOW_MS", "500"),
                 ("MXTPU_SLO_FAST_WINDOWS", "5"),
                 ("MXTPU_SLO_SLOW_WINDOW_S", "60"),
                 ("MXTPU_SLO_SERVE_P99_MS", "500")):
        os.environ.setdefault(k, v)
    _slo.stop()  # a fresh evaluator picks up the bench cadence

    repo = ModelRepository()
    t0 = time.perf_counter()
    model = repo.load("bench", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch, max_delay_ms=args.delay_ms,
                      queue_depth=max(256, args.clients * 4),
                      replicas=1, max_replicas=args.max_replicas)
    load_s = time.perf_counter() - t0
    model.min_replicas = 1
    pool = model.pool
    server = ServingServer(repo, port=0, addr="127.0.0.1").start()
    asc = server.attach_autoscaler(Autoscaler(
        repo, interval_ms=500, up_windows=2, idle_s=args.idle_s,
        cooldown_s=2.0))
    endpoint = ("127.0.0.1", server.port, "/v1/models/bench:predict")
    timeout_s = args.timeout_ms / 1e3 + 10.0
    shape = next(iter(input_shapes.values()))
    rng = np.random.RandomState(0)
    payloads = [_payload(rng.uniform(-1, 1, (1,) + shape).astype(np.float32),
                         args.timeout_ms) for _ in range(8)]

    # pool size/health timeline (the scale-up latency evidence)
    timeline, stop = [], threading.Event()

    def watch():
        last = None
        while not stop.is_set():
            cur = (pool.size, pool.healthy_count)
            if cur != last:
                timeline.append((time.perf_counter(), cur[0], cur[1]))
                last = cur
            time.sleep(0.01)

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()

    log("phase 1/4: baseline closed loop (%d clients x 10) ..."
        % args.clients)
    baseline = _closed_loop(endpoint, payloads, clients=args.clients,
                            requests_each=10, timeout_s=timeout_s)
    log("  baseline: %.1f rps p99=%.1fms" % (baseline["rps"],
                                             baseline["p99_ms"]))

    # the surge ships HEAVY requests (up to 8 examples each): the
    # overload is measured in examples/sec, so a batching-efficient pool
    # is still genuinely overdriven and the p99/queue objectives breach
    surge_n = min(8, model.max_batch)
    surge_payloads = [
        _payload(rng.uniform(-1, 1, (surge_n,) + shape).astype(np.float32),
                 args.timeout_ms) for _ in range(8)]
    surge_rate = args.surge_rate or max(150.0, 1.5 * baseline["rps"])
    log("phase 2/4: open-loop surge @ %.0f req/s x %d examples for "
        "%.0fs ..." % (surge_rate, surge_n, args.surge_duration))
    t_surge = time.perf_counter()
    surge = _open_loop(endpoint, surge_payloads, surge_rate,
                       args.surge_duration, timeout_s)
    t_surge_end = time.perf_counter()
    # scale-up latency: surge start -> the grown pool fully serving
    scale_up_s = None
    scaled_to = max((s for _, s, _ in timeline), default=1)
    if scaled_to > 1:
        serving = [t for t, s, h in timeline if s > 1 and h >= s]
        if serving:
            scale_up_s = serving[0] - t_surge
    log("  surge: %d reqs, codes=%s; scaled to %d (scale-up %.1fs)"
        % (surge["requests"], surge["codes"], scaled_to,
           scale_up_s or -1.0))

    log("phase 3/4: p99 recovery ...")
    objective = "serve-p99:%s/%d" % (model.name, model.version)
    recovery_s = None
    deadline = time.perf_counter() + 60.0
    while recovery_s is None and time.perf_counter() < deadline:
        v = next((v for v in _slo.verdicts() if v["slo"] == objective),
                 None)
        if v is not None and v["healthy"] and not v["no_data"]:
            recovery_s = time.perf_counter() - t_surge_end
            break
        time.sleep(0.25)
    log("  p99 verdict recovered in %s s" % (round(recovery_s, 2)
                                             if recovery_s else "NEVER"))

    log("phase 4/4: idle scale-down ...")
    scale_down_s = None
    deadline = time.perf_counter() + 60.0
    while pool.size > 1 and time.perf_counter() < deadline:
        time.sleep(0.25)
    if pool.size == 1 and scaled_to > 1:
        scale_down_s = time.perf_counter() - t_surge_end
    time.sleep(1.5)  # let the last remove's drain/decision records land
    stop.set()
    watcher.join(timeout=2.0)

    codes = dict(baseline["codes"])
    for c, n in surge["codes"].items():
        codes[str(c)] = codes.get(str(c), 0) + n
    snap = telemetry.snapshot()

    def decisions(action):
        return snap.get('mxtpu_autoscale_decisions_total{action="%s"}'
                        % action, {}).get("value", 0)

    result = {
        "mode": "serve_autoscale",
        "net": os.path.basename(args.model) if args.model else args.net,
        "device": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
                  else "default",
        "buckets": model.buckets,
        "load_s": round(load_s, 2),
        "baseline": dict(baseline, clients=args.clients),
        "surge": dict(surge, rate=surge_rate),
        "min_replicas": 1,
        "max_replicas": args.max_replicas,
        "scaled_to": scaled_to,
        "scale_up_latency_s": round(scale_up_s, 3)
                              if scale_up_s is not None else None,
        "p99_recovery_s": round(recovery_s, 3)
                          if recovery_s is not None else None,
        "scale_down_s": round(scale_down_s, 3)
                        if scale_down_s is not None else None,
        "final_replicas": pool.size,
        "codes": codes,
        "zero_500s": all(int(c) in (200, 429, 503, 504)
                         for c in codes),
        "retry_after_honored": baseline["retry_after_honored"],
        "decisions": {a: decisions(a)
                      for a in ("up", "down", "evict", "blocked")},
        "decision_trail": asc.describe()["decisions"],
        "size_timeline": [[round(t - t_surge, 3), s, h]
                          for t, s, h in timeline],
        "slo": _slo_block([_slo_sample("surge")], args.slo_spec),
    }
    log("autoscale: scaled 1->%d in %ss, p99 recovered %ss, down in %ss, "
        "codes=%s" % (scaled_to, result["scale_up_latency_s"],
                      result["p99_recovery_s"], result["scale_down_s"],
                      codes))
    server.drain(shutdown=True)
    telemetry.flush(reason="serve_bench_autoscale")
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--net", choices=("mlp", "resnet18"), default="mlp")
    p.add_argument("--model", default=None,
                   help="serve an existing artifact instead of building one "
                        "(export prefix or .mxc; needs --input for a prefix)")
    p.add_argument("--input", default=None, metavar="NAME=DIMS",
                   help="per-example input signature for --model prefixes, "
                        "e.g. data=3x224x224")
    p.add_argument("--image-size", type=int, default=32,
                   help="resnet18 spatial size (32 keeps CPU runs fast)")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--delay-ms", type=float, default=5.0)
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--requests", type=int, default=25,
                   help="closed-loop requests PER CLIENT per phase")
    p.add_argument("--seq-requests", type=int, default=None,
                   help="sequential-phase request count "
                        "(default: clients*requests capped at 200)")
    p.add_argument("--timeout-ms", type=float, default=30000.0,
                   help="per-request deadline used by EVERY phase (equal "
                        "latency budget across sequential and batched)")
    p.add_argument("--open-rate", type=float, default=0.0,
                   help="open-loop phase arrival rate per second (0 = skip)")
    p.add_argument("--open-duration", type=float, default=5.0)
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="distributed-tracing sample rate for the bench "
                        "(1.0 = every request contributes to the "
                        "per-phase breakdown; 0 disables spans)")
    p.add_argument("--generate", action="store_true",
                   help="run the decode row instead: a tiny decoder-only "
                        "LM served through the continuous-batching "
                        "scheduler + paged KV cache (tokens/sec, "
                        "inter-token p99, KV occupancy, jit-after-warm)")
    p.add_argument("--gen-vocab", type=int, default=512)
    p.add_argument("--gen-max-batch", type=int, default=8,
                   help="decode batch buckets = powers of two up to this")
    p.add_argument("--kv-pages", type=int, default=128)
    p.add_argument("--kv-page-size", type=int, default=8)
    p.add_argument("--max-prompt", type=int, default=16)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--slo-spec", default=None, metavar="PATH",
                   help="JSON SLO spec (MXTPU_SLO_SPEC format) loaded "
                        "before serving starts; the run's verdicts and "
                        "burn rates land in the output's `slo` block "
                        "either way (built-in objectives evaluate "
                        "without a spec)")
    p.add_argument("--failover", action="store_true",
                   help="run the resilience row instead of the throughput "
                        "phases: closed-loop load over a --replicas pool "
                        "with a SIGKILLed replica at --kill-after")
    p.add_argument("--autoscale", action="store_true",
                   help="run the elasticity row instead: open-loop surge "
                        "over a 1-replica pool with the autoscaler armed "
                        "(surge -> measured scale-up latency -> p99 "
                        "recovery -> idle scale-down)")
    p.add_argument("--surge-rate", type=float, default=0.0,
                   help="--autoscale surge arrival rate per second "
                        "(0 = 1.5x the measured baseline, min 150; each "
                        "surge request carries up to 8 examples)")
    p.add_argument("--surge-duration", type=float, default=8.0,
                   help="--autoscale surge length in seconds")
    p.add_argument("--max-replicas", type=int, default=3,
                   help="--autoscale ceiling")
    p.add_argument("--idle-s", dest="idle_s", type=float, default=4.0,
                   help="--autoscale idle window before scale-down")
    p.add_argument("--replicas", type=int, default=2,
                   help="pool size for --failover (>= 2 so the endpoint "
                        "survives a single-replica loss)")
    p.add_argument("--failover-duration", type=float, default=12.0,
                   help="closed-loop seconds for the --failover row")
    p.add_argument("--kill-after", type=float, default=3.0,
                   help="seconds into the --failover run to SIGKILL "
                        "replica 0")
    args = p.parse_args(argv)

    import numpy as np

    import mxnet_tpu  # noqa: F401  (package init pins platform handling)
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ModelRepository, ServingServer

    log = lambda msg: print("[serve_bench] " + msg, file=sys.stderr)  # noqa: E731

    # committed BENCH rows carry machine-readable health verdicts, not
    # just latency points: load any spec objectives up front and sample
    # verdicts/burn rates per phase (docs/observability.md §SLOs)
    if args.slo_spec:
        telemetry.slo.load_spec(args.slo_spec)
        telemetry.slo.start()

    if args.generate:
        return _run_generate(args, log)

    tmpdir = tempfile.mkdtemp(prefix="serve_bench_")
    input_shapes = None
    if args.model:
        prefix = args.model
        if args.input:
            iname, dims = args.input.split("=", 1)
            input_shapes = {iname: tuple(int(d) for d in dims.split("x"))}
    elif args.net == "resnet18":
        log("building resnet18_v1 (%dx%d) ..." % (args.image_size,
                                                  args.image_size))
        prefix, input_shapes = _build_resnet18(tmpdir, args.image_size)
    else:
        log("building mlp ...")
        prefix, input_shapes = _build_mlp(tmpdir)

    if args.failover:
        return _run_failover(args, prefix, input_shapes, log)

    if args.autoscale:
        return _run_autoscale(args, prefix, input_shapes, log)

    # per-phase peak-RSS bookkeeping (telemetry.memory): the serving
    # memory budget's committed CPU evidence needs real residency numbers
    # next to each phase's throughput
    def phase_mem():
        return telemetry.memory.read_process_memory() or {}

    mem_phases = {"start": phase_mem()}

    repo = ModelRepository()
    t0 = time.perf_counter()
    model = repo.load("bench", prefix, input_shapes=input_shapes,
                      max_batch=args.max_batch, max_delay_ms=args.delay_ms,
                      queue_depth=max(1024, args.clients * 4))
    load_s = time.perf_counter() - t0
    mem_phases["loaded"] = phase_mem()
    log("loaded buckets=%s warm=%.2fs" % (model.buckets,
                                          model.warm_seconds or 0.0))

    # executable-cache evidence: executor builds BEFORE traffic (warmup
    # compiles one forward per bucket; steady state must add zero)
    builds = telemetry.get_registry().counter(
        "mxtpu_executor_build_total", {"what": "forward"})
    builds_after_warm = builds.value

    # distributed tracing: sample bench traffic and collect spans in-
    # process (tracing.set_collector) for the per-phase breakdown
    tracing = telemetry.tracing
    spans = []
    if args.trace_sample > 0:
        tracing.configure(sample=min(1.0, args.trace_sample))
        tracing.set_collector(spans.append)

    server = ServingServer(repo, port=0, addr="127.0.0.1").start()
    endpoint = ("127.0.0.1", server.port, "/v1/models/bench:predict")
    timeout_s = args.timeout_ms / 1e3 + 10.0
    shape = next(iter(input_shapes.values()))
    rng = np.random.RandomState(0)

    one = [_payload(rng.uniform(-1, 1, (1,) + shape).astype(np.float32),
                    args.timeout_ms) for _ in range(8)]

    seq_n = args.seq_requests or min(200, args.clients * args.requests)
    log("phase 1/3: sequential x%d ..." % seq_n)
    seq = _closed_loop(endpoint, one, clients=1, requests_each=seq_n,
                       timeout_s=timeout_s)
    log("  sequential: %.1f req/s p50=%.1fms p99=%.1fms"
        % (seq["rps"], seq["p50_ms"], seq["p99_ms"]))
    mem_phases["sequential"] = phase_mem()
    slo_samples = [_slo_sample("sequential")]

    log("phase 2/3: batched closed-loop %d clients x%d ..."
        % (args.clients, args.requests))
    batched = _closed_loop(endpoint, one, clients=args.clients,
                           requests_each=args.requests, timeout_s=timeout_s)
    log("  batched: %.1f req/s p50=%.1fms p99=%.1fms"
        % (batched["rps"], batched["p50_ms"], batched["p99_ms"]))
    mem_phases["batched"] = phase_mem()
    slo_samples.append(_slo_sample("batched"))

    # mixed per-request example counts: every bucket gets traffic, and the
    # executable cache must already hold them all
    sizes = [s for s in (1, 2, 3, 4, 5, 7, 8) if s <= model.max_batch]
    mix_rng = random.Random(0)
    mixed_payloads = [
        _payload(rng.uniform(-1, 1, (mix_rng.choice(sizes),) + shape)
                 .astype(np.float32), args.timeout_ms)
        for _ in range(32)]
    builds_before_mixed = builds.value
    log("phase 3/3: mixed sizes %s ..." % sizes)
    mixed = _closed_loop(endpoint, mixed_payloads, clients=args.clients,
                         requests_each=max(4, args.requests // 2),
                         timeout_s=timeout_s)
    jit_after_warm = builds.value - builds_after_warm
    jit_in_mixed = builds.value - builds_before_mixed
    log("  mixed: %.1f req/s; jit compiles during traffic: %d"
        % (mixed["rps"], jit_after_warm))
    mem_phases["mixed"] = phase_mem()
    slo_samples.append(_slo_sample("mixed"))

    open_phase = None
    if args.open_rate > 0:
        log("open loop @ %.0f req/s for %.0fs ..." % (args.open_rate,
                                                      args.open_duration))
        open_phase = _open_loop(endpoint, one, args.open_rate, args.open_duration,
                                timeout_s)

    # occupancy evidence from the serving metrics themselves
    snap = telemetry.snapshot()
    label = '{model="%s/%d"}' % (model.name, model.version)
    occ = snap.get("mxtpu_serve_batch_occupancy" + label, {})
    bsz = snap.get("mxtpu_serve_batch_size" + label, {})
    batches = snap.get("mxtpu_serve_batches_total" + label, {}).get("value", 0)
    examples = snap.get("mxtpu_serve_examples_total" + label,
                        {}).get("value", 0)

    phases, slowest = _phase_breakdown(spans)
    if phases:
        log("  phase breakdown (p50 ms): %s" % {
            k: v["p50_ms"] for k, v in phases.items()})
    if slowest:
        log("  slowest request: %.1fms trace %s (render: python tools/"
            "trace_merge.py --trace %s -o slow.json <telemetry jsonl>)"
            % (slowest["total_ms"], slowest["trace_id"],
               slowest["trace_id"]))
    tracing.set_collector(None)
    tracing.configure()

    speedup = round(batched["rps"] / seq["rps"], 2) if seq["rps"] else None
    result = {
        "mode": "serve_bench",
        "net": os.path.basename(args.model) if args.model else args.net,
        "device": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
                  else "default",
        "buckets": model.buckets,
        "max_batch": model.max_batch,
        "delay_ms": args.delay_ms,
        "timeout_ms": args.timeout_ms,
        "load_s": round(load_s, 2),
        "warm_s": round(model.warm_seconds or 0.0, 2),
        "sequential": seq,
        "batched": dict(batched, clients=args.clients),
        "mixed": dict(mixed, sizes=sizes),
        "open": open_phase,
        "speedup_batched_vs_sequential": speedup,
        "jit_compiles_after_warmup": jit_after_warm,
        "jit_compiles_in_mixed_phase": jit_in_mixed,
        # span-derived per-phase latency split + the trace id to render
        # for the worst request (tools/trace_merge.py --trace <id>)
        "phases": phases or None,
        "slowest_request": slowest,
        "trace_sample": args.trace_sample,
        "bucket_flops": model.bucket_flops or None,
        # per-executable memory attribution of the served model (what the
        # MXTPU_SERVE_MEMORY_BUDGET admission check prices) + peak RSS at
        # each phase boundary (docs/observability.md §Memory)
        "model_memory": {"total_bytes": model.memory_bytes,
                         "per_bucket": {str(b): f for b, f in
                                        sorted(model.bucket_memory.items())}},
        "memory_phases": mem_phases,
        # machine-readable health verdicts sampled during the run
        # (docs/observability.md §SLOs): committed BENCH rows say whether
        # the run was healthy, not just how fast it went
        "slo": _slo_block(slo_samples, args.slo_spec),
        "occupancy": {
            "batches": batches,
            "examples": examples,
            "mean_batch": round(examples / batches, 2) if batches else None,
            "mean_fill": round(occ["sum"] / occ["count"], 3)
                         if occ.get("count") else None,
            "batch_size_hist": bsz.get("buckets"),
        },
    }
    server.drain(shutdown=True)
    telemetry.flush(reason="serve_bench")  # archive JSONL when dir is set
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
