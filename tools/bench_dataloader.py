"""Decode-bound DataLoader scaling benchmark (VERDICT round-1 item 6).

Builds an on-disk JPEG dataset and times epochs at several num_workers
settings. On a multi-core host the worker-process path scales with cores
(JPEG decode is GIL-bound Python/PIL work); on a single-core machine — like
this build's CI — workers can only add IPC overhead, so interpret results
accordingly (`nproc` is printed first).

Usage: python tools/bench_dataloader.py [num_images] [height width]
"""
from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


class JpegDS:
    """Module-level (hence picklable) so the DataLoader's host-purity probe
    admits real worker processes — a locally-defined class silently demoted
    the benchmark to the threaded fallback it exists to compare against."""

    def __init__(self, paths):
        self.paths = paths

    def __len__(self):
        return len(self.paths)

    def __getitem__(self, i):
        from PIL import Image

        img = np.asarray(Image.open(self.paths[i]).convert("RGB"))
        img = img[8:8 + 224, 8:8 + 224]
        if i % 2:
            img = img[:, ::-1]
        return (np.ascontiguousarray(img.transpose(2, 0, 1),
                                     dtype=np.float32),
                np.float32(i % 10))


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from PIL import Image

    from mxnet_tpu.gluon.data import DataLoader

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    h, w = (int(sys.argv[2]), int(sys.argv[3])) if len(sys.argv) > 3 \
        else (480, 640)

    print("cores:", os.cpu_count())
    tmp = tempfile.mkdtemp(prefix="mxtpu_dlbench_")
    rng = np.random.RandomState(0)
    paths = []
    for i in range(n):
        arr = rng.randint(0, 255, (h, w, 3), np.uint8)
        p = os.path.join(tmp, "i%d.jpg" % i)
        Image.fromarray(arr).save(p, quality=90)
        paths.append(p)

    for nw in (0, 2, 4, 8):
        dl = DataLoader(JpegDS(paths), batch_size=32, num_workers=nw)
        list(dl)  # warm: pool spin-up + page cache
        t0 = time.perf_counter()
        batches = sum(1 for _ in dl)
        dt = time.perf_counter() - t0
        print("num_workers=%d: %.2fs  %.0f imgs/s  (%d batches)"
              % (nw, dt, n / dt, batches))


if __name__ == "__main__":
    main()
