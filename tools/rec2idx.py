#!/usr/bin/env python
"""rec2idx: rebuild the .idx file for an existing RecordIO file
(equivalent of the reference's tools/rec2idx.py: walks the record stream
recording byte offsets)."""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_index(rec_path, idx_path):
    from mxnet_tpu import recordio

    # force the python reader: it exposes tell() positions for free and the
    # native reader is only used for the (hot) training path
    os.environ["MXTPU_PY_RECORDIO"] = "1"
    try:
        reader = recordio.MXRecordIO(rec_path, "r")
        count = 0
        with open(idx_path, "w") as f:
            while True:
                pos = reader.tell()
                buf = reader.read()
                if buf is None:
                    break
                f.write("%d\t%d\n" % (count, pos))
                count += 1
        reader.close()
    finally:
        os.environ.pop("MXTPU_PY_RECORDIO", None)
    return count


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", nargs="?", help="output .idx path")
    args = p.parse_args(argv)
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"
    n = build_index(args.record, idx)
    print("indexed %d records -> %s" % (n, idx))


if __name__ == "__main__":
    main()
