"""On-chip MFU probe: locate where ResNet-50 train MFU is lost.

Measures, on the real accelerator:
  1. full DistributedTrainer step (fwd+bwd+SGD update, AMP master weights)
  2. the segment decomposition shared with bench.py's train mode
     (`bench._mfu_segments`): raw bf16 matmul ceiling, fwd-only, and
     fwd + dgrad chain (grad w.r.t. input — ~2x fwd FLOPs, no wgrad)

Prints one JSON line with achieved TFLOP/s and MFU vs the chip's bf16
peak, so the gap analysis (docs/perf_notes.md) is grounded in measurements
rather than guesses. The segment harness lives in bench.py (one
implementation — train bench artifacts and this probe must never compute
segment MFU differently).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _mfu_segments  # noqa: E402 — shared segment harness
from mxnet_tpu.runtime import chip_peak_tflops as _chip_peak_tflops  # noqa: E402

import numpy as np  # noqa: E402

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 10))
FWD_FLOPS = 8.178e9   # ResNet-50 224x224 fwd FLOPs/img (BASELINE.md)
TRAIN_FLOPS = 3 * FWD_FLOPS


def main():
    import jax

    dev = jax.devices()[0]
    peak = _chip_peak_tflops(dev)
    out = {"device": getattr(dev, "device_kind", str(dev)), "batch": BATCH,
           "peak_bf16_tflops": peak}

    # ---- build net + batch ----------------------------------------------
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    ctx = mx.tpu()
    with ctx:
        net = vision.resnet50_v1()
        net.initialize(ctx=ctx)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.uniform(-1, 1, (BATCH, 3, 224, 224))
                        .astype(np.float32), ctx=ctx)
        y = mx.nd.array(rng.randint(0, 1000, (BATCH,)).astype(np.float32),
                        ctx=ctx)
        net(x)

    # ---- 1. full trainer step (before segments: they cast the net) ------
    mesh = make_mesh([("dp", 1)], devices=[dev])
    trainer = DistributedTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype="bfloat16")
    trainer.step(x, y).asnumpy()
    for _ in range(3):
        trainer.step(x, y)
    trainer.step(x, y).asnumpy()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = trainer.step(x, y)
    loss.asnumpy()
    dt_step = (time.perf_counter() - t0) / ITERS
    tf = BATCH * TRAIN_FLOPS / dt_step / 1e12
    out["train_step_ms"] = round(dt_step * 1e3, 2)
    out["train_tflops"] = round(tf, 1)
    if peak:
        out["train_mfu"] = round(tf / peak, 4)

    # ---- 2. shared segment decomposition (matmul / fwd / fwd+dgrad) -----
    _mfu_segments(out, dev, net, ctx, x, FWD_FLOPS, iters=ITERS)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
