"""On-chip MFU probe: locate where ResNet-50 train MFU is lost.

Measures, on the real accelerator:
  1. raw bf16 matmul ceiling (what the tunnel+chip can actually sustain)
  2. ResNet-50 forward-only (pure bf16 inference jit) at a given batch
  3. ResNet-50 fwd+bwd via jax.grad of the bf16 loss (no optimizer)
  4. full DistributedTrainer step (fwd+bwd+SGD update, AMP master weights)

Prints one JSON line with achieved TFLOP/s and MFU vs the chip's bf16
peak, so the gap analysis (docs/perf_notes.md) is grounded in measurements
rather than guesses.
"""
import json
import os
import time

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mxnet_tpu.runtime import chip_peak_tflops as _chip_peak_tflops

import numpy as np

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 10))
FWD_FLOPS = 8.178e9   # ResNet-50 224x224 fwd FLOPs/img (BASELINE.md)
TRAIN_FLOPS = 3 * FWD_FLOPS


def timed(fn, *args, n=ITERS):
    fn(*args)  # compile
    for _ in range(2):
        fn(*args)
    _block(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn(*args)
    _block(out)
    return (time.perf_counter() - t0) / n


def _block(x):
    # drain via host fetch: on the remote-PJRT tunnel block_until_ready can
    # return before remote execution completes; device_get cannot
    import jax
    jax.device_get(jax.tree.leaves(x)[0] if not hasattr(x, "dtype") else x)


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    peak = _chip_peak_tflops(dev)  # bench.py maintains the per-chip table
    out = {"device": getattr(dev, "device_kind", str(dev)), "batch": BATCH,
           "peak_bf16_tflops": peak}

    # ---- 1. raw matmul ceiling ------------------------------------------
    # chain k dependent matmuls inside one jit so the device can't elide
    # repeated identical dispatches (zeros-in/zeros-out with a constant
    # operand measured 276x peak -> clearly shortcut somewhere); random
    # data + a dependent chain forces real MXU work per iteration.
    n = 8192
    k = 8
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n),
                          jnp.float32).astype(jnp.bfloat16)

    @jax.jit
    def mm(p, q):
        for _ in range(k):
            p = (p @ q) * jnp.bfloat16(1e-4)  # rescale to avoid inf
        return p

    dt = timed(mm, a, b) / k
    out["matmul_8192_tflops"] = round(2 * n ** 3 / dt / 1e12, 1)
    if peak:
        out["matmul_mfu"] = round(2 * n ** 3 / dt / 1e12 / peak, 4)

    # ---- build net + batch ----------------------------------------------
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh
    from __graft_entry__ import _pure_forward

    ctx = mx.tpu()
    with ctx:
        net = vision.resnet50_v1()
        net.initialize(ctx=ctx)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.uniform(-1, 1, (BATCH, 3, 224, 224))
                        .astype(np.float32), ctx=ctx)
        y = mx.nd.array(rng.randint(0, 1000, (BATCH,)).astype(np.float32),
                        ctx=ctx)
        net(x)

    # ---- 4. full trainer step (before cast: trainer owns AMP) -----------
    mesh = make_mesh([("dp", 1)], devices=[dev])
    trainer = DistributedTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype="bfloat16")
    trainer.step(x, y).asnumpy()
    for _ in range(3):
        trainer.step(x, y)
    trainer.step(x, y).asnumpy()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = trainer.step(x, y)
    loss.asnumpy()
    dt_step = (time.perf_counter() - t0) / ITERS
    tf = BATCH * TRAIN_FLOPS / dt_step / 1e12
    out["train_step_ms"] = round(dt_step * 1e3, 2)
    out["train_tflops"] = round(tf, 1)
    if peak:
        out["train_mfu"] = round(tf / peak, 4)

    # ---- 2. pure bf16 forward -------------------------------------------
    net.cast("bfloat16")
    fwd = _pure_forward(net, ctx)
    jitted = jax.jit(fwd)
    xb = x._data.astype(jnp.bfloat16)
    dt_f = timed(jitted, xb)
    tf_f = BATCH * FWD_FLOPS / dt_f / 1e12
    out["fwd_ms"] = round(dt_f * 1e3, 2)
    out["fwd_tflops"] = round(tf_f, 1)
    if peak:
        out["fwd_mfu"] = round(tf_f / peak, 4)

    # ---- 3. fwd+bwd (grad of mean-logit-sum loss, pure bf16) ------------
    grad_fn = jax.jit(jax.grad(lambda d: fwd(d).astype(jnp.float32).sum()))
    dt_g = timed(grad_fn, xb)
    tf_g = BATCH * TRAIN_FLOPS / dt_g / 1e12
    out["fwdbwd_ms"] = round(dt_g * 1e3, 2)
    out["fwdbwd_tflops"] = round(tf_g, 1)
    if peak:
        out["fwdbwd_mfu"] = round(tf_g / peak, 4)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
