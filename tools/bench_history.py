#!/usr/bin/env python
"""bench_history: aggregate committed ``BENCH_*.json`` evidence into one
trajectory table.

20+ bench artifacts are committed at the repo root (bench.py rows,
serve_bench, failover, coldstart, memory rows — every PR adds more), but
a reviewer asking "how has throughput moved across PRs?" has to open
them one by one. This tool reads every ``BENCH_*.json``, extracts each
row's headline figure with schema-aware extractors (the artifacts were
never one schema and never will be — stale/error rows are kept and
labeled, not hidden), and writes:

  * ``docs/bench_trajectory.md`` — the human table, sorted by capture
    round then row name;
  * ``BENCH_TRAJECTORY.json`` — the machine-readable rows (plots, CI
    trend checks).

Run it directly or let ``tools/bench_capture.sh`` append the current
capture's rows at the end of every run:

    python tools/bench_history.py [--root DIR] [--quiet]

``--check`` turns the trajectory from write-only evidence into a
regression gate: for each headline metric family (serving rps, decode
tokens/sec, failover rps, cold-start time-to-ready, training MFU), the
newest round's row is compared against the BEST prior non-stale,
non-failed row of the same kind; a >15% regression (``--tolerance``)
prints a table and exits 2. ``bench_capture.sh`` runs it warn-only at
the end of every capture; CI can run it blocking.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

# the row group is LAZY so a trailing `_stale` relabel (bench_capture.sh
# dial-failure path) lands in the stale group instead of being swallowed
# into the row name — stale captures must render as stale
_NAME_RE = re.compile(r"BENCH_(?:(?P<scope>local)_)?r(?P<round>\d+)"
                      r"(?:_(?P<row>[A-Za-z0-9_]+?))?(?P<stale>_stale)?"
                      r"\.json$")


def _fmt(v, nd=2):
    if v is None:
        return ""
    if isinstance(v, float):
        return ("%%.%df" % nd) % v
    return str(v)


def _extract(doc):
    """(metric, value, unit, detail) headline for one artifact, by schema
    family. Unknown schemas degrade to a labeled raw row, never a skip."""
    if not isinstance(doc, dict):
        return ("unparsed", None, "", "non-object JSON")
    # bench_capture probe-failure rows ({"n":..,"rc":..,"tail":..} or
    # explicit error/stale labels)
    if doc.get("error") or ("rc" in doc and doc.get("rc") not in (0, None)):
        return ("capture_failed", None, "",
                str(doc.get("error") or "rc=%s" % doc.get("rc"))[:60])
    mode = doc.get("mode")
    if mode == "serve_bench":
        b = doc.get("batched") or {}
        s = doc.get("sequential") or {}
        detail = "seq %s rps, x%s, p99 %sms" % (
            _fmt(s.get("rps"), 1),
            _fmt(doc.get("speedup_batched_vs_sequential")),
            _fmt(b.get("p99_ms"), 1))
        return ("serve_batched_rps", b.get("rps"), "req/s", detail)
    if mode == "serve_decode":
        kv = doc.get("kv") or {}
        detail = "inter-token p99 %sms, kv peak %s/%s pages, %s jit " \
                 "after warm" % (
                     _fmt(doc.get("intertoken_p99_ms"), 1),
                     _fmt(kv.get("peak_pages_used"), 0),
                     _fmt(kv.get("pages_total"), 0),
                     _fmt(doc.get("jit_compiles_after_warmup"), 0))
        return ("decode_tokens_per_sec", doc.get("tokens_per_sec"),
                "tok/s", detail)
    if mode == "serve_failover":
        lw = doc.get("loss_window") or {}
        return ("failover_rps", doc.get("rps_overall"), "req/s",
                "loss-window %s rps, %s errors, recovery %ss" % (
                    _fmt(lw.get("rps"), 1), _fmt(doc.get("unresolved"), 0),
                    _fmt(doc.get("recovery_s"), 1)))
    if mode == "serve_autoscale":
        return ("autoscale_scale_up_s", doc.get("scale_up_latency_s"), "s",
                "1->%s replicas, p99 recovered %ss, down %ss, 500s=%s" % (
                    _fmt(doc.get("scaled_to"), 0),
                    _fmt(doc.get("p99_recovery_s"), 1),
                    _fmt(doc.get("scale_down_s"), 1),
                    "no" if doc.get("zero_500s") else "YES"))
    if mode == "serve_memory":
        return ("serve_memory", doc.get("footprint_bytes"), "bytes",
                "budget reject=%s accept=%s, donation aliased=%s" % (
                    doc.get("over_budget_rejected"),
                    doc.get("within_budget_accepted"),
                    _fmt((doc.get("donation") or {}).get(
                        "aliased_fraction"))))
    metric = doc.get("metric") or ""
    if metric.startswith("coldstart"):
        warm, cold = doc.get("warm") or {}, doc.get("cold") or {}
        return (metric, warm.get("ready_s"), "s ready (warm)",
                "cold %ss, x%s, %s jit on warm" % (
                    _fmt(cold.get("ready_s"), 1),
                    _fmt(doc.get("ready_speedup")),
                    _fmt(warm.get("jit_compiles"), 0)))
    if "train_sharded" in metric and "value" in doc:
        # the hot-path promotion A/B row (bench.py bench_train_sharded):
        # surface the fused-vs-op-by-op evidence, the dispatch-overhead
        # delta, the donation aliasing and the data-wait share
        detail = []
        if doc.get("speedup_fused_vs_opbyop") is not None:
            detail.append("x%s vs op-by-op"
                          % _fmt(doc["speedup_fused_vs_opbyop"]))
        if doc.get("dispatch_per_step_opbyop") is not None:
            detail.append("dispatch %s->%s/step" % (
                _fmt(doc["dispatch_per_step_opbyop"], 0),
                _fmt(doc.get("dispatch_per_step_fused"), 0)))
        if doc.get("aliased_fraction") is not None:
            detail.append("aliased %s" % _fmt(doc["aliased_fraction"]))
        if doc.get("data_wait_fraction") is not None:
            detail.append("wait %s%%"
                          % _fmt(100 * doc["data_wait_fraction"], 1))
        if doc.get("stale"):
            detail.append("STALE")
        return (metric, doc.get("value"), doc.get("unit") or "",
                ", ".join(detail))
    if "train_input" in metric and "value" in doc:
        # the input-pipeline A/B row (bench.py bench_train_input):
        # headline is the prefetched imgs/sec; detail surfaces the
        # data-wait contrast and the row's self-checks (loss-trajectory
        # equality, post-warm compiles, attributor coverage)
        detail = []
        if doc.get("speedup_prefetched_vs_sync") is not None:
            detail.append("x%s vs sync"
                          % _fmt(doc["speedup_prefetched_vs_sync"]))
        if doc.get("data_wait_fraction_sync") is not None:
            detail.append("wait %s%%->%s%%" % (
                _fmt(100 * doc["data_wait_fraction_sync"], 1),
                _fmt(100 * (doc.get("data_wait_fraction_prefetched")
                            or 0.0), 1)))
        if doc.get("data_wait_reduction") is not None:
            detail.append("wait /%s" % _fmt(doc["data_wait_reduction"], 1))
        if doc.get("loss_trajectory_match") is False:
            detail.append("TRAJECTORY DIVERGED")
        if doc.get("jit_compiles_after_warm"):
            detail.append("%s jit after warm"
                          % _fmt(doc["jit_compiles_after_warm"], 0))
        if doc.get("goodput_coverage_prefetched") is not None:
            detail.append("coverage %s"
                          % _fmt(doc["goodput_coverage_prefetched"]))
        if doc.get("platform"):
            detail.append(str(doc["platform"]))
        if doc.get("stale"):
            detail.append("STALE")
        return (metric, doc.get("value"), doc.get("unit") or "",
                ", ".join(detail))
    if metric == "train_goodput" and "value" in doc:
        # the goodput-attribution A/B row (bench.py bench_train_goodput):
        # headline is the attributed goodput fraction; detail surfaces the
        # stall mix and whether the legacy fit split and the attributor
        # still agree on data-wait (the row's self-check)
        gp = doc.get("goodput") or {}
        fr = gp.get("phase_fractions") or {}
        detail = []
        if fr.get("data_wait") is not None:
            detail.append("wait %s%%" % _fmt(100 * fr["data_wait"], 1))
        stalls = {p: v for p, v in fr.items()
                  if p not in ("compute", "data_wait")}
        if stalls:
            top = max(stalls.items(), key=lambda kv: kv[1])
            detail.append("top stall %s %s%%" % (top[0],
                                                 _fmt(100 * top[1], 1)))
        if doc.get("ab_data_wait_ratio") is not None:
            detail.append("A/B x%s%s" % (
                _fmt(doc["ab_data_wait_ratio"]),
                "" if doc.get("ab_agree_within_10pct") else " DISAGREE"))
        if doc.get("platform"):
            detail.append(str(doc["platform"]))
        if doc.get("stale"):
            detail.append("STALE")
        return (metric, doc.get("value"), doc.get("unit") or "fraction",
                ", ".join(detail))
    if metric == "train_preempt_ckpt_stall" and "value" in doc:
        # the async-vs-sync checkpoint stall A/B (train_restart_bench.py
        # --mode preempt): per-save trainer stall plus the measured
        # steps-lost contrast between a hard kill and a graceful preempt
        sy, asy = doc.get("sync") or {}, doc.get("async") or {}
        lost = doc.get("steps_lost") or {}
        detail = ["sync %sms -> async %sms/save" % (
            _fmt((sy.get("per_save_stall_s") or 0) * 1e3, 0),
            _fmt((asy.get("per_save_stall_s") or 0) * 1e3, 0))]
        if lost:
            detail.append("lost kill=%s preempt=%s" % (
                _fmt(lost.get("steps_lost_hard_kill"), 0),
                _fmt(lost.get("steps_lost_graceful_preempt"), 0)))
        if doc.get("payload_bytes"):
            detail.append("%sMB payload"
                          % _fmt(doc["payload_bytes"] / (1 << 20), 0))
        if doc.get("stale"):
            detail.append("STALE")
        return (metric, doc.get("value"), doc.get("unit") or "x",
                ", ".join(detail))
    if metric and "value" in doc:
        detail = []
        if doc.get("mfu") is not None:
            detail.append("MFU %s" % _fmt(doc["mfu"], 3))
        if doc.get("data_wait_fraction") is not None:
            # data-wait vs compute split of the timed region (train rows)
            detail.append("wait %s%%"
                          % _fmt(100 * doc["data_wait_fraction"], 1))
        if doc.get("vs_baseline") is not None:
            detail.append("x%s vs %s" % (_fmt(doc["vs_baseline"]),
                                         (doc.get("baseline") or {}).get(
                                             "hw", "baseline")))
        if doc.get("stale"):
            detail.append("STALE")
        return (metric, doc.get("value"), doc.get("unit") or "",
                ", ".join(detail))
    return ("unknown_schema", None, "",
            ", ".join(sorted(doc)[:6]))


def collect(root):
    """One trajectory row per BENCH_*.json under ``root``."""
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        base = os.path.basename(path)
        if base == "BENCH_TRAJECTORY.json":
            continue
        m = _NAME_RE.match(base)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            doc = {"error": "unreadable: %s" % e}
        metric, value, unit, detail = _extract(doc)
        device = doc.get("device") or doc.get("backend") \
            if isinstance(doc, dict) else None
        utc = doc.get("utc") if isinstance(doc, dict) else None
        if not utc:
            utc = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                time.gmtime(os.path.getmtime(path)))
        rows.append({
            "file": base,
            "round": int(m.group("round")) if m else None,
            "row": (m.group("row") if m else None) or "",
            "stale": bool(m and m.group("stale")) or bool(
                isinstance(doc, dict) and doc.get("stale")),
            "metric": metric,
            "value": value,
            "unit": unit,
            "device": device,
            # MFU rides along where the artifact reports it, so --check
            # can gate on it next to the throughput headline
            "mfu": doc.get("mfu") if isinstance(doc, dict) else None,
            "detail": detail,
            "utc": utc,
        })
    rows.sort(key=lambda r: (r["round"] if r["round"] is not None else 999,
                             r["row"], r["file"]))
    return rows


def render_markdown(rows):
    lines = [
        "# Bench trajectory",
        "",
        "Generated by `python tools/bench_history.py` from the committed",
        "`BENCH_*.json` evidence files (one row each; `bench_capture.sh`",
        "refreshes this table at the end of every capture). `capture_failed`",
        "rows are kept — a stale/failed capture is evidence too",
        "(ROADMAP item 5).",
        "",
        "| Round | Row | Metric | Value | Unit | Device | Detail | File |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        detail = (r["detail"] or "-").replace("|", "/")
        if r["stale"]:
            detail = ("**STALE** " + detail).rstrip(" -")
        lines.append("| %s | %s | %s | %s | %s | %s | %s | `%s` |" % (
            "r%02d" % r["round"] if r["round"] is not None else "?",
            r["row"] or "-", r["metric"],
            _fmt(r["value"]), r["unit"] or "-", r["device"] or "-",
            detail, r["file"]))
    lines += ["",
              "%d artifact(s); machine-readable mirror: "
              "`BENCH_TRAJECTORY.json`." % len(rows), ""]
    return "\n".join(lines)


# headline metric families the --check gate compares across rounds, and
# which direction is "better". Values are per-row `metric` names from
# `_extract`; MFU is gated separately off each row's `mfu` field.
_CHECK_METRICS = {
    "serve_batched_rps": "higher",
    "decode_tokens_per_sec": "higher",
    "failover_rps": "higher",
    "coldstart_ready": "lower",     # warm time-to-ready, seconds
    # (includes coldstart_train_*: fused-restart time-to-step-1)
    "autoscale_scale_up_s": "lower",  # surge -> grown pool serving
    "train_sharded": "higher",      # promotion A/B imgs/sec, per impl+bs
    "train_input": "higher",        # prefetch A/B imgs/sec, per batch
    "train_preempt_ckpt_stall": "higher",  # sync/async stall reduction, x
    "train_goodput": "higher",      # attributed goodput fraction of wall
}


def _check_one(label, newest, best, direction, tolerance):
    """One comparison row, or None when within tolerance. ``newest`` and
    ``best`` are (value, file) pairs."""
    if not newest[0] or not best[0]:
        return None
    if direction == "higher":
        change = (best[0] - newest[0]) / best[0]
    else:
        change = (newest[0] - best[0]) / best[0]
    if change <= tolerance:
        return None
    return {"metric": label, "newest": newest[0], "newest_file": newest[1],
            "best_prior": best[0], "best_file": best[1],
            "regression_pct": round(change * 100.0, 1),
            "direction": direction}


def check(rows, tolerance=0.15):
    """Regression gate over trajectory rows: for each headline family,
    newest-round row vs the best prior NON-STALE, non-failed row. Returns
    the list of regressions (empty = gate passes)."""
    regressions = []
    usable = [r for r in rows
              if not r["stale"] and r["round"] is not None
              and r["metric"] not in ("capture_failed", "unparsed",
                                      "unknown_schema")]

    def gate(label, group, value_of, direction):
        group = [r for r in group if value_of(r)]
        if len(group) < 2:
            return  # nothing to compare against — not a failure
        newest_round = max(r["round"] for r in group)
        newest = [r for r in group if r["round"] == newest_round]
        prior = [r for r in group if r["round"] < newest_round]
        if not prior:
            return
        pick = max if direction == "higher" else min
        best = pick(prior, key=value_of)
        new = pick(newest, key=value_of)  # best of the newest round
        hit = _check_one(label, (value_of(new), new["file"]),
                         (value_of(best), best["file"]), direction,
                         tolerance)
        if hit:
            regressions.append(hit)

    for metric, direction in _CHECK_METRICS.items():
        if metric == "coldstart_ready":
            # coldstart metric names are per-model-geometry
            # (coldstart_resnet18_mb8, ...): gate each family on its own
            # history — comparing different models' ready-times would
            # both false-alarm and mask real regressions
            names = sorted({str(r["metric"]) for r in usable
                            if str(r["metric"]).startswith("coldstart")})
            for name in names:
                gate(name, [r for r in usable if r["metric"] == name],
                     lambda r: r["value"], direction)
            continue
        if metric in ("train_sharded", "train_input"):
            # per-impl-per-batch families (mlp_train_sharded_fused_bs256_
            # imgs_per_sec, mlp_train_input_prefetch_bs256_..., ...):
            # each name gates on its own history — racing configs would
            # mask one family's regression behind another's improvement
            names = sorted({str(r["metric"]) for r in usable
                            if metric in str(r["metric"])})
            for name in names:
                gate(name, [r for r in usable if r["metric"] == name],
                     lambda r: r["value"], direction)
            continue
        gate(metric, [r for r in usable if r["metric"] == metric],
             lambda r: r["value"], direction)
    # MFU gate: per (metric, row) family so train MFU never races score MFU
    mfu_rows = [r for r in usable if r.get("mfu")]
    for key in sorted({(r["metric"], r["row"]) for r in mfu_rows}):
        group = [r for r in mfu_rows
                 if (r["metric"], r["row"]) == key]
        gate("mfu:%s/%s" % key, group, lambda r: r.get("mfu"), "higher")
    return regressions


def render_check_table(regressions):
    lines = ["| Metric | Newest | Best prior | Regression | Files |",
             "|---|---|---|---|---|"]
    for r in regressions:
        lines.append("| %s | %s | %s | %.1f%% | `%s` vs `%s` |" % (
            r["metric"], _fmt(r["newest"]), _fmt(r["best_prior"]),
            r["regression_pct"], r["newest_file"], r["best_file"]))
    return "\n".join(lines)


def run_check(root, tolerance, quiet=False):
    """The --check entry: prefer the committed BENCH_TRAJECTORY.json
    (what reviewers see), fall back to a fresh collect()."""
    traj = os.path.join(root, "BENCH_TRAJECTORY.json")
    rows = None
    if os.path.exists(traj):
        try:
            with open(traj) as f:
                rows = json.load(f).get("rows")
        except (OSError, ValueError) as e:
            sys.stderr.write("[bench_history] unreadable %s (%s); "
                             "re-collecting\n" % (traj, e))
    if not rows:
        rows = collect(root)
    regressions = check(rows, tolerance)
    if regressions:
        sys.stderr.write(
            "[bench_history] REGRESSION: %d headline metric(s) worse than "
            "%.0f%% vs the best prior non-stale row:\n%s\n"
            % (len(regressions), tolerance * 100.0,
               render_check_table(regressions)))
        return 2
    if not quiet:
        sys.stderr.write("[bench_history] check ok: no headline metric "
                         ">%.0f%% below its best prior non-stale row "
                         "(%d rows)\n" % (tolerance * 100.0, len(rows)))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=None,
                   help="repo root holding BENCH_*.json (default: the "
                        "checkout this tool lives in)")
    p.add_argument("--check", action="store_true",
                   help="regression gate: compare the newest round's "
                        "headline metrics against the best prior "
                        "non-stale row; exit 2 and print a table on "
                        "a regression beyond --tolerance")
    p.add_argument("--tolerance", type=float, default=0.15,
                   help="--check regression tolerance as a fraction "
                        "(default 0.15 = 15%%)")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    if args.check:
        return run_check(root, args.tolerance, quiet=args.quiet)
    rows = collect(root)
    md_path = os.path.join(root, "docs", "bench_trajectory.md")
    os.makedirs(os.path.dirname(md_path), exist_ok=True)
    with open(md_path, "w") as f:
        f.write(render_markdown(rows))
    json_path = os.path.join(root, "BENCH_TRAJECTORY.json")
    with open(json_path, "w") as f:
        json.dump({"generated_by": "tools/bench_history.py",
                   "rows": rows}, f, indent=1)
        f.write("\n")
    if not args.quiet:
        sys.stderr.write("[bench_history] %d rows -> %s + %s\n"
                         % (len(rows), md_path, json_path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
