"""On-chip op-level profile of the ResNet-50 bs256 bf16 train step.

Captures an xplane trace of a few steady-state DistributedTrainer steps
(the exact executable bench.py times) and prints the top HLO ops by self
time, aggregated by category (conv fwd/dgrad/wgrad, fusions, reductions,
...). This answers what docs/perf_notes.md's whole-model/per-shape
contradiction leaves open: per-shape conv kernels run near peak
(conv_probe), yet the model's backward runs at ~1/4 of forward
efficiency — so the time must be in ops the per-shape probe doesn't see.

Usage: python tools/step_profile.py [--net resnet50_v1] [--batch 256]
Writes step_trace/ and prints a JSON summary per op category, plus a
rollup onto the goodput phase vocabulary (telemetry/goodput.py) so these
on-silicon xplane rows line up with tools/goodput_report.py's CPU-side
attribution rows: device collectives land in `collective`, everything
else the device executes is `compute`.
"""
import argparse
import glob
import json
import os


def capture(trace_dir, net_name, batch):
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    ctx = mx.tpu()
    with ctx:
        factory = getattr(vision, net_name, None)
        if factory is None:
            raise SystemExit("--net %r: no such model_zoo.vision model"
                             % net_name)
        net = factory()
        net.initialize(ctx=ctx)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.uniform(-1, 1, (batch, 3, 224, 224))
                        .astype(np.float32), ctx=ctx)
        y = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32),
                        ctx=ctx)
        net(x)
    mesh = make_mesh([("dp", 1)], devices=[jax.devices()[0]])
    tr = DistributedTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype="bfloat16")
    for _ in range(3):
        tr.step(x, y)
    tr.step(x, y).asnumpy()  # drain
    jax.profiler.start_trace(trace_dir)
    for _ in range(3):
        tr.step(x, y)
    tr.step(x, y).asnumpy()
    jax.profiler.stop_trace()


def summarize(trace_dir):
    """Aggregate device-track op self-times from the trace-events JSON
    (vm.trace.json.gz — same content as the xplane, no proto deps)."""
    import gzip

    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not paths:
        print(json.dumps({"error": "no trace.json.gz captured"}))
        return
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # map pid/tid -> track name; device tracks are the TensorCore ones
    procs = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            procs[ev["pid"]] = ev["args"].get("name", "")
    dev_pids = {pid for pid, nm in procs.items()
                if "TPU" in nm or "/device" in nm.lower()}
    cats = {}
    ops = {}
    total_us = 0.0
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in dev_pids:
            continue
        nm = ev.get("name", "")
        # XLA module / step envelope events nest the real op events;
        # skip them so times aren't double-counted
        if nm.startswith("jit_") or "XLA Modules" in nm:
            continue
        dur = float(ev.get("dur", 0.0))
        total_us += dur
        cats[classify(nm)] = cats.get(classify(nm), 0.0) + dur
        ops[nm] = ops.get(nm, 0.0) + dur
    phases = {}
    for cat, us in cats.items():
        p = goodput_phase(cat)
        phases[p] = phases.get(p, 0.0) + us
    out = {
        "device_tracks": sorted(procs[p] for p in dev_pids),
        "trace_total_ms": round(total_us / 1e3, 2),
        "by_category_ms": {k: round(v / 1e3, 2) for k, v in
                           sorted(cats.items(), key=lambda kv: -kv[1])},
        "by_goodput_phase_ms": {k: round(v / 1e3, 2) for k, v in
                                sorted(phases.items(),
                                       key=lambda kv: -kv[1])},
        "top_ops_ms": {k: round(v / 1e3, 2) for k, v in
                       sorted(ops.items(), key=lambda kv: -kv[1])[:40]},
    }
    print(json.dumps(out, indent=1))


def classify(nm):
    n = nm.lower()
    if "convolution" in n or "conv" in n:
        return "conv"
    if "dot" in n:
        return "dot"
    if "reduce-window" in n or "select-and-scatter" in n:
        return "pooling"
    if "all-reduce" in n or "collective" in n:
        return "collective"
    if "reduce" in n:
        return "reduce"
    if "fusion" in n:
        return "fusion"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "layout"
    if "scatter" in n or "gather" in n or "dynamic" in n:
        return "scatter_gather"
    return "other"


def goodput_phase(category):
    """Map a device-op category onto the goodput phase vocabulary
    (telemetry/goodput.py PHASES). On the device track only two phases
    exist: cross-replica communication is `collective`, all other
    executed HLO is `compute` — host phases (data_wait, host_dispatch,
    compile, checkpoint_stall) never appear on a device track."""
    return "collective" if category == "collective" else "compute"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--net", default="resnet50_v1",
                    help="gluon.model_zoo.vision factory name "
                         "(default resnet50_v1)")
    ap.add_argument("--batch", type=int, default=256,
                    help="global batch size (default 256)")
    args = ap.parse_args(argv)
    d = os.environ.get("MXTPU_STEP_TRACE_DIR", "step_trace")
    capture(d, args.net, args.batch)
    summarize(d)


if __name__ == "__main__":
    main()
