"""On-chip bisect of the ResNet-50 bs256 train-step MFU gap (round 4).

step_profile.py shows the step is fusion-dominated (~65% of device time in
elementwise/reduce fusions vs 20% in convs). This script attributes that
time to components by timing the same DistributedTrainer step with pieces
knocked out:

  full        — the bench configuration (train-mode BN)
  bn_frozen   — BatchNorm use_global_stats=True (no batch stats; affine
                + running stats only; backward still reduces dgamma/dbeta)
  bn_identity — BatchNorm monkeypatched to identity (isolates ALL BN cost)
  relu_identity — Activation monkeypatched to identity (isolates ReLU
                mask traffic fwd+bwd)

Each timing: warmup, drain, free-running ITERS loop, asnumpy drain
(docs/perf_notes.md methodology — only a host fetch bounds the region).
"""
import json
import os
import time

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 20))


def build_and_time(bn_mode="train", relu_identity=False):
    import jax
    import numpy as np

    import mxnet_tpu as mx
    import mxnet_tpu.ops as _ops
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    patched = []

    def patch(name, fn):
        op = _ops._REGISTRY[name]
        patched.append((op, op.fn))
        op.fn = fn

    try:
        if bn_mode == "identity":
            # arity-preserving identity: BatchNorm returns (out, mm, mv)
            patch("BatchNorm", lambda d, g, b, mm, mv, **kw: (d, mm, mv))
        elif bn_mode == "frozen":
            orig = _ops._REGISTRY["BatchNorm"].fn
            patch("BatchNorm", lambda *a, **kw: orig(
                *a, **{**kw, "use_global_stats": True}))
        if relu_identity:
            patch("Activation", lambda d, act_type="relu", **kw: d)

        ctx = mx.tpu()
        with ctx:
            net = vision.resnet50_v1()
            net.initialize(ctx=ctx)
            rng = np.random.RandomState(0)
            x = mx.nd.array(rng.uniform(-1, 1, (BATCH, 3, 224, 224))
                            .astype(np.float32), ctx=ctx)
            y = mx.nd.array(rng.randint(0, 1000, (BATCH,))
                            .astype(np.float32), ctx=ctx)
            net(x)
        mesh = make_mesh([("dp", 1)], devices=[jax.devices()[0]])
        tr = DistributedTrainer(
            net, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
            loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
            amp_dtype="bfloat16")
        for _ in range(5):
            tr.step(x, y)
        tr.step(x, y).asnumpy()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = tr.step(x, y)
        loss.asnumpy()
        dt = (time.perf_counter() - t0) / ITERS
        return dt
    finally:
        for op, fn in patched:
            op.fn = fn


def main():
    res = {}
    for tag, kw in [
        ("full", {}),
        ("bn_frozen", {"bn_mode": "frozen"}),
        ("bn_identity", {"bn_mode": "identity"}),
        ("relu_identity", {"relu_identity": True}),
    ]:
        dt = build_and_time(**kw)
        res[tag] = round(dt * 1e3, 2)
        print(json.dumps({tag: res[tag]}), flush=True)
    flops = BATCH * 3 * 2 * 4.089e9
    out = {"batch": BATCH, "step_ms": res,
           "mfu_full": round(flops / (res["full"] / 1e3) / 1e12 / 197, 4)}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
