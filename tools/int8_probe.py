"""INT8 vs bf16 kernel probe: does int8 pay on this chip, per shape?

Answers the round-4 finding that the int8 scoring path is SLOWER than
bf16 (BENCH_local_r04_score_int8: 3502 vs 5644 img/s). Three hypotheses:
(a) XLA doesn't lower s8xs8->s32 convs to the MXU int8 path and upcasts
instead, (b) the conv itself is fast but the requantize epilogue
(scale/round/clip/cast between layers) breaks fusion, (c) overhead
elsewhere. This probe times, per ResNet-50 bulk shape:

  - bf16 conv            (the fp baseline the quantized path must beat)
  - int8 conv -> int32   (raw quantized kernel)
  - int8 conv + requantize epilogue -> int8 (the deployed pattern)

and the same trio for a big FC-shaped dot. Methodology identical to
tools/conv_probe.py: chained fori_loop with a full-reduce carry, one RTT
subtracted (see docs/perf_notes.md "Timing methodology").

Run on the chip: python tools/int8_probe.py   (writes JSONL to stdout)
"""
import json
import os
import time

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 200))

# (cin, cout, hw, k, stride) — ResNet-50 bulk shapes (conv_probe.py list)
SHAPES = [
    (64, 64, 56, 3, 1),
    (64, 256, 56, 1, 1),
    (128, 128, 28, 3, 1),
    (256, 256, 14, 3, 1),
    (512, 512, 7, 3, 1),
    (256, 512, 28, 1, 2),
]

_RTT = None


def _rtt():
    global _RTT
    if _RTT is None:
        import jax
        import jax.numpy as jnp

        tiny = jax.jit(lambda v: v + 1.0)
        z = jnp.zeros((), jnp.float32)
        float(tiny(z))
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(tiny(z))
            samples.append(time.perf_counter() - t0)
        _RTT = min(samples)
        print(json.dumps({"rtt_ms": round(_RTT * 1e3, 3)}), flush=True)
    return _RTT


def _timed(loop, *args):
    float(loop(*args))
    t0 = time.perf_counter()
    float(loop(*args))
    return max(time.perf_counter() - t0 - _rtt(), 1e-9) / ITERS


def main():
    import jax

    # a sitecustomize PJRT hook force-overrides jax_platforms at
    # interpreter start; honor an explicit CPU request (smoke tests)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    def chain(val):
        return jnp.sum(val, dtype=jnp.float32) * 1e-30

    def probe_conv(cin, cout, hw, k, s):
        pad = k // 2
        ho = hw // s
        flops = 2 * BATCH * cout * ho * ho * cin * k * k
        xs = (BATCH, cin, hw, hw)
        ws = (cout, cin, k, k)
        key = jax.random.PRNGKey(0)
        xf = jax.random.normal(key, xs, jnp.float32)
        wf = jax.random.normal(jax.random.PRNGKey(1), ws, jnp.float32)
        xb, wb = xf.astype(jnp.bfloat16), wf.astype(jnp.bfloat16)
        xi = jnp.clip(jnp.round(xf * 20), -127, 127).astype(jnp.int8)
        wi = jnp.clip(jnp.round(wf * 20), -127, 127).astype(jnp.int8)

        def conv(xx, ww, pet=None):
            kw = {"preferred_element_type": pet} if pet is not None else {}
            return lax.conv_general_dilated(
                xx, ww, window_strides=(s, s),
                padding=[(pad, pad), (pad, pad)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"), **kw)

        @jax.jit
        def bf16_loop(x, w):
            def body(_, c):
                return chain(conv(x, w + c.astype(w.dtype)))
            return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

        @jax.jit
        def int8_loop(x, w):
            def body(_, c):
                # perturb via the int8 weight: XOR with a 0/1 derived
                # from the carry — unlike `w + bit`, XOR cannot wrap int8
                # (127+1 -> -128 flipped perturbed weights to the extreme,
                # so the int8 and bf16 loops computed on slightly different
                # weight distributions)
                wp = w ^ (c * 1e30).astype(jnp.int8)  # c ~ 1e-30 -> 0 or 1
                return chain(conv(x, wp, jnp.int32))
            return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

        @jax.jit
        def int8_rq_loop(x, w):
            def body(_, c):
                wp = w ^ (c * 1e30).astype(jnp.int8)
                acc = conv(x, wp, jnp.int32)
                # deployed epilogue: static-scale requantize to int8
                q = jnp.clip(jnp.round(acc.astype(jnp.float32) * 7.3e-4),
                             -127, 127).astype(jnp.int8)
                return chain(q)
            return lax.fori_loop(0, ITERS, body, jnp.zeros((), jnp.float32))

        row = {"cin": cin, "cout": cout, "hw": hw, "k": k, "s": s}
        for name, loop, a, b in (("bf16", bf16_loop, xb, wb),
                                 ("int8", int8_loop, xi, wi),
                                 ("int8_rq", int8_rq_loop, xi, wi)):
            try:
                dt = _timed(loop, a, b)
                row[name + "_tflops"] = round(flops / dt / 1e12, 1)
            except Exception as e:  # noqa: BLE001 — record, keep probing
                row[name + "_error"] = str(e)[:120]
        print(json.dumps(row), flush=True)

    def probe_dot(m, kk, n):
        flops = 2 * m * kk * n
        key = jax.random.PRNGKey(2)
        af = jax.random.normal(key, (m, kk), jnp.float32)
        bf = jax.random.normal(jax.random.PRNGKey(3), (kk, n), jnp.float32)
        ab, bb = af.astype(jnp.bfloat16), bf.astype(jnp.bfloat16)
        ai = jnp.clip(jnp.round(af * 20), -127, 127).astype(jnp.int8)
        bi = jnp.clip(jnp.round(bf * 20), -127, 127).astype(jnp.int8)

        def loops(pet):
            @jax.jit
            def loop(a, b):
                def body(_, c):
                    if pet is jnp.int32:  # int8 operands: XOR, no wraparound
                        bp = b ^ (c * 1e30).astype(b.dtype)
                    else:
                        bp = b + c.astype(b.dtype)
                    kw = {"preferred_element_type": pet} if pet else {}
                    return chain(jnp.dot(a, bp, **kw))
                return lax.fori_loop(0, ITERS, body,
                                     jnp.zeros((), jnp.float32))
            return loop

        row = {"dot": [m, kk, n]}
        for name, loop, a, b in (("bf16", loops(None), ab, bb),
                                 ("int8", loops(jnp.int32), ai, bi)):
            try:
                dt = _timed(loop, a, b)
                row[name + "_tflops"] = round(flops / dt / 1e12, 1)
            except Exception as e:  # noqa: BLE001
                row[name + "_error"] = str(e)[:120]
        print(json.dumps(row), flush=True)

    dev = jax.devices()[0]
    print(json.dumps({"device": getattr(dev, "device_kind", str(dev)),
                      "batch": BATCH, "iters": ITERS}), flush=True)
    probe_dot(4096, 4096, 4096)
    for shp in SHAPES:
        probe_conv(*shp)


if __name__ == "__main__":
    main()
