"""Probe 2 (single-trainer): train step with optimizer update replaced by
identity, at MXTPU_PROBE_BATCH (default 256). Compare against the full-step
number from bench.py / probe 1 to isolate the optimizer-update cost from
the train-mode-BN + loss cost."""
import json
import os
import time

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mxnet_tpu.runtime import chip_peak_tflops as _chip_peak_tflops

import numpy as np

BATCH = int(os.environ.get("MXTPU_PROBE_BATCH", 256))
ITERS = int(os.environ.get("MXTPU_PROBE_ITERS", 10))
TRAIN_FLOPS = 3 * 8.178e9


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    dev = jax.devices()[0]
    peak = _chip_peak_tflops(dev)  # bench.py maintains the per-chip table
    out = {"device": getattr(dev, "device_kind", str(dev)), "batch": BATCH,
           "segment": "noupdate_step"}

    ctx = mx.tpu()
    with ctx:
        net = vision.resnet50_v1()
        net.initialize(ctx=ctx)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.uniform(-1, 1, (BATCH, 3, 224, 224))
                        .astype(np.float32), ctx=ctx)
        y = mx.nd.array(rng.randint(0, 1000, (BATCH,)).astype(np.float32),
                        ctx=ctx)
        net(x)

    mesh = make_mesh([("dp", 1)], devices=[dev])
    tr = DistributedTrainer(
        net, "sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
        amp_dtype="bfloat16")
    tr._traced_update = lambda weights, grads, states, t, lr: \
        (list(weights), list(states))
    tr.step(x, y).asnumpy()
    for _ in range(3):
        tr.step(x, y)
    tr.step(x, y).asnumpy()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = tr.step(x, y)
    loss.asnumpy()
    dt = (time.perf_counter() - t0) / ITERS
    out["step_ms"] = round(dt * 1e3, 2)
    if peak:
        out["mfu"] = round(BATCH * TRAIN_FLOPS / dt / 1e12 / peak, 4)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
