"""Fused-restart cold-start bench: TRAINING time-to-step-1, cold vs warm
persistent compile cache (docs/sharded_training.md, docs/compile_cache.md).

The serving coldstart bench (tools/coldstart_bench.py) proves the replica
path; this one proves the ShardedTrainer quarantine lift — that a fused
sharded+donated TRAIN step round-trips the persistent artifact tier.
It spawns the same tiny promoted-trainer job TWICE against one
``MXTPU_COMPILE_CACHE`` directory:

  * run 1 (**cold**): empty cache — the whole-step executable is traced,
    compiled, verified for donation aliasing, persisted, and recorded in
    the trainer's warmup manifest;
  * run 2 (**restart**): a fresh process rebuilds the same trainer; its
    topology-fingerprinted key digests identically, the manifest
    prefetches, and the acceptance contract is ZERO ``jit_compile``
    events in its telemetry (exit 4 otherwise) with a measurably lower
    time-to-step-1.

One JSON row on stdout (``bench_capture.sh`` archives it as
``BENCH_<tag>_train_restart.json``; ``coldstart_train_*`` metrics join
the coldstart family in ``tools/bench_history.py --check``).

``--mode preempt`` runs the CHECKPOINT-STALL A/B instead (ISSUE 17): the
same periodic sharded-checkpoint schedule over a multi-megabyte payload,
once with the synchronous writer (``MXTPU_CKPT_ASYNC=0`` — every save
blocks the step loop for the full serialize+fsync) and once with the
async writer (the step loop pays only the host snapshot + submit). The
row reports per-save stall seconds for both, their ratio (the headline
``train_preempt_ckpt_stall`` value — acceptance wants >=10x), and the
steps-lost-on-preempt comparison: a hard kill between periodic saves
loses the steps since the last checkpoint, a graceful preemption's
emergency checkpoint loses ZERO (both measured by actually restoring).
Exits 5 when async stall reduction falls below 2x.

Usage: python tools/train_restart_bench.py [--steps 4] [--cache-dir DIR]
       python tools/train_restart_bench.py --mode preempt
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def log(msg):
    sys.stderr.write("[train_restart_bench] %s\n" % msg)
    sys.stderr.flush()


def _jsonl_events(tdir):
    counts = {}
    for name in sorted(os.listdir(tdir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(tdir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "event":
                    ev = rec.get("event")
                    counts[ev] = counts.get(ev, 0) + 1
    return counts


def _jsonl_goodput(tdir):
    """Goodput phase breakdown from the life's final telemetry flush (the
    same counters tools/goodput_report.py joins): per-phase seconds +
    fractions of step wall and the attributed goodput fraction. None when
    the life published no goodput counters (telemetry disabled)."""
    prefix = 'mxtpu_goodput_phase_seconds_total{phase="'
    phases, wall = {}, 0.0
    for name in sorted(os.listdir(tdir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(tdir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") != "metrics":
                    continue
                for key, snap in (rec.get("metrics") or {}).items():
                    if key.startswith(prefix):
                        phase = key[len(prefix):].rstrip('"}')
                        phases[phase] = float(snap.get("value") or 0.0)
                    elif key == "mxtpu_goodput_wall_seconds_total":
                        wall = float(snap.get("value") or 0.0)
    if wall <= 0.0:
        return None
    phases.pop("between_steps", None)  # loop idle — not part of step wall
    phases = {p: v for p, v in phases.items() if v > 0.0}
    return {"phase_seconds": {p: round(v, 4) for p, v in phases.items()},
            "phase_fractions": {p: round(v / wall, 4)
                                for p, v in phases.items()},
            "goodput_fraction": round(phases.get("compute", 0.0) / wall, 4),
            "step_wall_s": round(wall, 4)}


def _worker(steps):
    """One training life: build the promoted trainer, time to the first
    completed fused step (trace + compile or persist-load + run), then a
    few steady steps. Prints one JSON line."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn, loss as gloss

    np.random.seed(0)
    mx.random.seed(0)
    t0 = time.monotonic()
    ctx = mx.cpu()
    net = nn.HybridSequential(prefix="tr_")
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu", prefix="fc1_"))
        net.add(nn.Dense(10, prefix="fc2_"))
    net.initialize(ctx=ctx)
    x = mx.nd.array(np.random.uniform(-1, 1, (16, 32)).astype(np.float32))
    y = mx.nd.array(np.random.randint(0, 10, (16,)).astype(np.float32))
    net(x)
    trainer = gluon.Trainer(
        net.collect_params(), "sgd", {"learning_rate": 0.05},
        sharded=True, block=net, loss=gloss.SoftmaxCrossEntropyLoss())
    loss = float(trainer.step_batch(x, y).asscalar())
    ready_s = time.monotonic() - t0
    for _ in range(steps - 1):
        loss = float(trainer.step_batch(x, y).asscalar())
    print(json.dumps({"ready_s": round(ready_s, 3),
                      "total_s": round(time.monotonic() - t0, 3),
                      "steps": steps, "final_loss": round(loss, 6),
                      "manifest_id": trainer.sharded.manifest_id,
                      "topology": trainer.sharded.topology}))
    return 0


def _spawn_run(tag, steps, cache_dir, workdir, timeout_s):
    tdir = os.path.join(workdir, "telemetry_" + tag)
    os.makedirs(tdir, exist_ok=True)
    env = dict(os.environ, MXTPU_COMPILE_CACHE=cache_dir,
               MXTPU_TELEMETRY_DIR=tdir, PYTHONPATH=_ROOT)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--steps", str(steps)],
        env=env, capture_output=True, text=True, timeout=timeout_s)
    if r.returncode != 0:
        raise RuntimeError("%s worker failed rc=%d:\n%s"
                           % (tag, r.returncode, r.stderr[-2000:]))
    row = json.loads(r.stdout.strip().splitlines()[-1])
    events = _jsonl_events(tdir)
    row["jit_compiles"] = events.get("jit_compile", 0)
    row["persist_hits"] = events.get("compile_persist_hit", 0)
    row["persist_bad"] = events.get("compile_persist_bad", 0)
    row["manifest_prefetches"] = events.get("sharded_manifest_prefetch", 0)
    gp = _jsonl_goodput(tdir)
    if gp is not None:
        row["goodput"] = gp
    return row


def _preempt_ab(save_period, saves, payload_mb, step_ms):
    """The checkpoint-stall A/B (no jax compute — the payload is the
    variable under test; CheckpointManager is the real code path). Each
    "step" sleeps `step_ms` standing in for device compute: that idle
    time is exactly what the async writer overlaps serialization with,
    and what the synchronous writer cannot use."""
    import numpy as np

    from mxnet_tpu.parallel.resilience import CheckpointManager

    n_arrays = 8
    per = max(1, int(payload_mb * (1 << 20) / 8 / n_arrays))
    base = {"w%d" % i: np.random.RandomState(i).standard_normal(per)
            for i in range(n_arrays)}
    payload_bytes = sum(a.nbytes for a in base.values())

    def snapshot():
        # the honest async stall includes the host snapshot the trainer
        # integration pays (shard_snapshot's device_get copies)
        return {k: v.copy() for k, v in base.items()}

    def phase(tag, async_on):
        os.environ["MXTPU_CKPT_ASYNC"] = "1" if async_on else "0"
        workdir = tempfile.mkdtemp(prefix="preempt_ab_%s_" % tag)
        mgr = CheckpointManager(workdir, keep_last=2)
        stalls = []
        total_steps = save_period * saves
        for step in range(1, total_steps + 1):
            # "training": mutate the live buffers so the snapshot matters,
            # then the stand-in compute
            base["w0"][:8] = step
            time.sleep(step_ms / 1000.0)
            if step % save_period == 0:
                t0 = time.monotonic()
                mgr.save_sharded_async(step, snapshot(), rank=0,
                                       world_size=1,
                                       topology={"world_size": 1})
                stalls.append(time.monotonic() - t0)
        mgr.close()
        assert mgr.latest()[0] == total_steps
        stalls.sort()
        # headline is the MEDIAN: steady-state per-save stall, robust to a
        # single disk-contention outlier on a shared CI box (max is kept)
        return {"per_save_stall_s": round(stalls[len(stalls) // 2], 6),
                "mean_stall_s": round(sum(stalls) / len(stalls), 6),
                "max_stall_s": round(max(stalls), 6),
                "saves": len(stalls)}

    log("phase 1/2: SYNC saves (MXTPU_CKPT_ASYNC=0, %.0f MB payload)"
        % (payload_bytes / (1 << 20)))
    sync = phase("sync", async_on=False)
    log("sync: %.1f ms/save" % (sync["per_save_stall_s"] * 1e3))
    log("phase 2/2: ASYNC saves (same schedule, same payload)")
    asyn = phase("async", async_on=True)
    log("async: %.1f ms/save" % (asyn["per_save_stall_s"] * 1e3))
    return sync, asyn, payload_bytes


def _steps_lost(save_period, preempt_step):
    """Measured (not derived) steps-lost comparison: run the periodic
    schedule to `preempt_step`, then restore from what each failure mode
    leaves behind — a hard kill leaves only the last periodic manifest, a
    graceful preemption also lands the emergency checkpoint."""
    from mxnet_tpu.parallel.resilience import CheckpointManager

    def run(emergency):
        workdir = tempfile.mkdtemp(prefix="preempt_lost_")
        mgr = CheckpointManager(workdir, keep_last=3)
        os.environ["MXTPU_CKPT_ASYNC"] = "1"
        for step in range(1, preempt_step + 1):
            if step % save_period == 0:
                mgr.save_sharded_async(step, {"step": step}, rank=0,
                                       world_size=1)
        if emergency:  # the maybe_preempt_exit emergency save
            mgr.flush()
            mgr.save_sharded(preempt_step, {"step": preempt_step}, rank=0,
                             world_size=1, meta={"preempt": True})
        mgr.close()
        got = {}
        mgr2 = CheckpointManager(workdir, keep_last=3)
        mgr2.restore_sharded(lambda p, h: got.update(p))
        return preempt_step - got[0]["step"]

    return {"steps_lost_hard_kill": run(emergency=False),
            "steps_lost_graceful_preempt": run(emergency=True),
            "preempt_step": preempt_step, "save_period": save_period}


def _preempt_main(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sync, asyn, payload_bytes = _preempt_ab(args.save_period, args.saves,
                                            args.payload_mb, args.step_ms)
    reduction = (sync["per_save_stall_s"] / asyn["per_save_stall_s"]
                 if asyn["per_save_stall_s"] else None)
    # preempt one step before the next periodic save: the worst case for
    # a hard kill, the non-case for a graceful preemption
    lost = _steps_lost(args.save_period,
                       args.save_period * args.saves + args.save_period - 1)
    try:
        sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, cwd=_ROOT,
                             timeout=10).stdout.strip() or None
    except Exception:
        sha = None
    result = {
        "metric": "train_preempt_ckpt_stall",
        "value": round(reduction, 1) if reduction else None,
        "unit": "x",
        "sync": sync,
        "async": asyn,
        "steps_lost": lost,
        "payload_bytes": payload_bytes,
        "save_period": args.save_period,
        "step_ms": args.step_ms,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "device",
        "sha": sha,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    log("stall reduction: x%.1f (sync %.1f ms -> async %.1f ms per save); "
        "steps lost: kill=%d preempt=%d"
        % (reduction or 0, sync["per_save_stall_s"] * 1e3,
           asyn["per_save_stall_s"] * 1e3, lost["steps_lost_hard_kill"],
           lost["steps_lost_graceful_preempt"]))
    # loose tool gate (2x) so CI noise can't flake; the committed artifact
    # carries the real figure the acceptance (>=10x) reads
    return 0 if reduction and reduction >= 2.0 \
        and lost["steps_lost_graceful_preempt"] == 0 else 5


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--mode", choices=["restart", "preempt"],
                   default="restart",
                   help="restart: cold-vs-warm compile cache (default); "
                        "preempt: sync-vs-async checkpoint stall A/B")
    p.add_argument("--steps", type=int, default=4,
                   help="fused steps per life (step 1 is the timed one)")
    p.add_argument("--save-period", type=int, default=3,
                   help="preempt mode: steps between periodic checkpoints")
    p.add_argument("--saves", type=int, default=6,
                   help="preempt mode: periodic checkpoints per phase")
    p.add_argument("--payload-mb", type=float, default=48.0,
                   help="preempt mode: checkpoint payload size")
    p.add_argument("--step-ms", type=float, default=180.0,
                   help="preempt mode: stand-in per-step compute time; the "
                        "idle the async writer overlaps serialization with")
    p.add_argument("--cache-dir", default=None,
                   help="persistent cache dir (default: fresh temp dir)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-life budget (seconds)")
    args = p.parse_args(argv)

    if args.worker:
        return _worker(args.steps)

    if args.mode == "preempt":
        return _preempt_main(args)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the bench process itself never trains; nothing here may seed the
    # cache the COLD life must find empty
    workdir = tempfile.mkdtemp(prefix="train_restart_bench_")
    cache_dir = args.cache_dir or os.path.join(workdir, "compile_cache")
    os.makedirs(cache_dir, exist_ok=True)

    log("life 1/2: COLD (empty cache %s)" % cache_dir)
    cold = _spawn_run("cold", args.steps, cache_dir, workdir, args.timeout)
    log("cold: step-1 %.2fs, %d jit_compiles"
        % (cold["ready_s"], cold["jit_compiles"]))

    artifacts, artifact_bytes = 0, 0
    objects = os.path.join(cache_dir, "objects")
    if os.path.isdir(objects):
        for name in os.listdir(objects):
            artifacts += 1
            artifact_bytes += os.path.getsize(os.path.join(objects, name))

    log("life 2/2: RESTART (warm cache)")
    warm = _spawn_run("warm", args.steps, cache_dir, workdir, args.timeout)
    log("restart: step-1 %.2fs, %d jit_compiles, %d persist hits"
        % (warm["ready_s"], warm["jit_compiles"], warm["persist_hits"]))

    try:
        sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, cwd=_ROOT,
                             timeout=10).stdout.strip() or None
    except Exception:
        sha = None
    result = {
        "metric": "coldstart_train_sharded_mlp",
        "steps": args.steps,
        "cold": cold,
        "warm": warm,
        "ready_speedup": round(cold["ready_s"] / warm["ready_s"], 2)
        if warm["ready_s"] else None,
        "zero_compile_on_warm": warm["jit_compiles"] == 0,
        # a restart that recompiled nothing must still have trained: the
        # two lives are numerically the same schedule from the same seed
        "loss_match": cold["final_loss"] == warm["final_loss"],
        "cache_artifacts": artifacts,
        "cache_bytes": artifact_bytes,
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "device",
        "sha": sha,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    json.dump(result, sys.stdout, indent=1)
    sys.stdout.write("\n")
    # acceptance: the restarted life must not have compiled anything
    return 0 if result["zero_compile_on_warm"] else 4


if __name__ == "__main__":
    sys.exit(main())
